"""RPC client: connect to a control-plane server, register services,
call remote services.

API shape mirrors what the reference gets from hypha-rpc's
``connect_to_server`` (a server object with register_service /
get_service / generate_token, ref bioengine/worker/worker.py:522-612),
so worker/app code reads the same against our in-repo control plane.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any, Callable, Optional

import aiohttp

from bioengine_tpu.rpc import protocol
from bioengine_tpu.rpc.schema import extract_schema
from bioengine_tpu.rpc.transport import (
    Codec,
    TransportConfig,
    attach_store_by_name,
)
from bioengine_tpu.utils.logger import create_logger
from bioengine_tpu.utils.tasks import spawn_supervised


class ServiceProxy:
    """Callable facade over a remote service: ``await svc.method(...)``."""

    def __init__(self, connection: "ServerConnection", service_info: dict):
        self._connection = connection
        self._info = service_info
        self.id = service_info["id"]

    def __getattr__(self, name: str) -> Callable:
        if name.startswith("_"):
            raise AttributeError(name)

        async def call(*args, **kwargs):
            return await self._connection.call(self.id, name, *args, **kwargs)

        call.__name__ = name
        return call

    def __repr__(self) -> str:
        return f"<ServiceProxy {self.id} methods={self._info.get('methods')}>"


class ServerConnection:
    """A live WebSocket session with the RPC server."""

    def __init__(
        self,
        url: str,
        token: Optional[str] = None,
        timeout: float = 300.0,
        shm_store: Any = "auto",
        transport_config: Optional[TransportConfig] = None,
        protocols: Optional[list[str]] = None,
    ):
        self.url = url
        self.token = token
        self.timeout = timeout
        # capabilities declared at handshake; [] forces pure-legacy
        # framing in BOTH directions (bench baseline, interop tests)
        self.protocols = (
            [protocol.PROTO_OOB1] if protocols is None else list(protocols)
        )
        self.client_id: Optional[str] = None
        self.workspace: Optional[str] = None
        self.user_id: Optional[str] = None
        self.logger = create_logger("rpc.client", log_file="off")
        self._session: Optional[aiohttp.ClientSession] = None
        self._ws: Optional[aiohttp.ClientWebSocketResponse] = None
        self._pending: dict[str, asyncio.Future] = {}
        self._local_services: dict[str, dict[str, Callable]] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self.codec = Codec(config=transport_config or TransportConfig.from_env())
        self._shm_store_cfg = shm_store
        self._owns_shm = False

    async def connect(self) -> "ServerConnection":
        self._session = aiohttp.ClientSession()
        url = self.url
        # declare codec support at handshake; a pre-oob server ignores
        # unknown query params and its welcome carries no "protocols",
        # so both sides settle on legacy frames automatically
        if self.protocols:
            sep = "&" if "?" in url else "?"
            url = f"{url}{sep}proto={','.join(self.protocols)}"
        if self.token:
            sep = "&" if "?" in url else "?"
            url = f"{url}{sep}token={self.token}"
        self._ws = await self._session.ws_connect(
            url, max_msg_size=self.codec.config.max_msg_size
        )
        welcome = self.codec.decode((await self._ws.receive()).data)
        self.client_id = welcome["client_id"]
        self.workspace = welcome["workspace"]
        self.user_id = welcome["user_id"]
        self.codec.oob = protocol.PROTO_OOB1 in self.protocols and (
            protocol.PROTO_OOB1 in welcome.get("protocols", [])
        )
        self._reader_task = asyncio.create_task(self._read_loop())
        if self.codec.oob and isinstance(welcome.get("shm"), dict):
            await self._negotiate_shm(welcome["shm"])
        return self

    async def _negotiate_shm(self, offer: dict) -> None:
        """Same-host handshake: map the server's segment, read the
        probe nonce out of it, echo it back. Any failure leaves the
        connection on wire frames — never fatal."""
        store = self._shm_store_cfg
        if store == "auto":
            store = attach_store_by_name(offer.get("name", ""))
            self._owns_shm = store is not None
        if store is None:
            return
        try:
            nonce = store.get_bytes(offer["probe_key"])
        except Exception:  # noqa: BLE001 — foreign/mismatched segment
            nonce = None
        if nonce is None:
            if self._owns_shm:
                store.close()
                self._owns_shm = False
            return
        verified = await self._request(
            {"t": protocol.SHM_ACK, "nonce": nonce}
        )
        if verified:
            self.codec.enable_shm(store)
            self.logger.info("shm fast path negotiated")
        elif self._owns_shm:
            store.close()
            self._owns_shm = False

    async def disconnect(self) -> None:
        if self._reader_task:
            self._reader_task.cancel()
        if self._ws:
            await self._ws.close()
        if self._session:
            await self._session.close()
        shm = self.codec.shm_store
        self.codec.close()
        if shm is not None and self._owns_shm:
            shm.close()

    def describe(self) -> dict:
        """Data-plane counters for this connection (mirrors
        RpcServer.describe)."""
        return {
            "url": self.url,
            "connected": self.connected,
            "oob": self.codec.oob,
            "shm": self.codec.shm_store.name
            if self.codec.shm_store is not None
            else None,
            "transport": self.codec.stats.as_dict(),
        }

    @property
    def connected(self) -> bool:
        return self._ws is not None and not self._ws.closed

    # ---- request/response ---------------------------------------------------

    async def _read_loop(self) -> None:
        assert self._ws is not None
        try:
            async for msg in self._ws:
                if msg.type != aiohttp.WSMsgType.BINARY:
                    continue
                try:
                    data = await self.codec.decode_async(msg.data)
                except Exception as e:  # noqa: BLE001
                    # a poisoned message (e.g. its shm object was
                    # evicted before we consumed it) must cost only
                    # that message — the affected call times out, the
                    # connection and every other in-flight call live
                    self.logger.error(f"dropping undecodable message: {e}")
                    continue
                finally:
                    # retry releasing pins of earlier shm payloads
                    # whose consumers have since dropped their views
                    # (results are handed to caller futures, so the
                    # release point is only observable opportunistically)
                    self.codec.drain_pins()
                if data is None:
                    continue  # mid-reassembly chunk
                t = data.get("t")
                if t in (protocol.RESULT, protocol.ERROR):
                    fut = self._pending.pop(data.get("call_id", ""), None)
                    if fut and not fut.done():
                        if t == protocol.RESULT:
                            fut.set_result(data.get("result"))
                        else:
                            err = data.get("error")
                            if not isinstance(err, Exception):
                                err = RuntimeError(str(err))
                            fut.set_exception(err)
                elif t == protocol.CALL:
                    spawn_supervised(
                        self._handle_incoming_call(data),
                        name="rpc-incoming-call",
                        logger=self.logger,
                    )
                elif t == protocol.PONG:
                    fut = self._pending.pop("__ping__", None)
                    if fut and not fut.done():
                        fut.set_result(data.get("ts"))
        except asyncio.CancelledError:
            pass

    async def _send_msg(self, msg: dict) -> None:
        assert self._ws is not None, "not connected"
        for frame in await self.codec.encode_frames_async(msg):
            await self._ws.send_bytes(frame)

    async def _request(self, msg: dict) -> Any:
        call_id = uuid.uuid4().hex
        msg["call_id"] = call_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[call_id] = fut
        await self._send_msg(msg)
        return await asyncio.wait_for(fut, self.timeout)

    async def _handle_incoming_call(self, msg: dict) -> None:
        """The server is routing another client's call to one of OUR
        registered services."""
        assert self._ws is not None
        try:
            service = self._local_services[msg["service_id"]]
            fn = service[msg["method"]]
            result = fn(*msg.get("args", []), **msg.get("kwargs", {}))
            if asyncio.iscoroutine(result):
                result = await result
            await self._send_msg(
                {
                    "t": protocol.RESULT,
                    "call_id": msg.get("call_id"),
                    "result": result,
                }
            )
        except Exception as e:
            await self._send_msg(
                {
                    "t": protocol.ERROR,
                    "call_id": msg.get("call_id"),
                    "error": e,
                }
            )
        finally:
            # args decoded from shm refs die with the handler — let the
            # store reclaim their blocks
            self.codec.drain_pins()

    # ---- public API (hypha-shaped) ------------------------------------------

    async def register_service(self, definition: dict[str, Any]) -> dict:
        methods = {k: v for k, v in definition.items() if callable(v)}
        schemas = {
            k: getattr(v, "__schema__", extract_schema(v))
            for k, v in methods.items()
        }
        wire_def = {k: v for k, v in definition.items() if not callable(v)}
        wire_def["methods"] = schemas
        result = await self._request(
            {"t": protocol.REGISTER, "definition": wire_def}
        )
        full_id = result["id"]
        self._local_services[full_id] = methods
        return {"id": full_id}

    async def unregister_service(self, service_id: str) -> None:
        await self._request(
            {"t": protocol.UNREGISTER, "service_id": service_id}
        )
        self._local_services.pop(service_id, None)

    async def list_services(self, workspace: Optional[str] = None) -> list[dict]:
        return await self._request(
            {"t": protocol.LIST, "workspace": workspace}
        )

    async def get_service(self, service_id: str) -> ServiceProxy:
        services = await self.list_services()
        for info in services:
            if info["id"] == service_id or info["id"].endswith(f"/{service_id}"):
                return ServiceProxy(self, info)
        raise KeyError(f"Service '{service_id}' not found")

    async def call(self, service_id: str, method: str, *args, **kwargs) -> Any:
        return await self._request(
            {
                "t": protocol.CALL,
                "service_id": service_id,
                "method": method,
                "args": list(args),
                "kwargs": kwargs,
            }
        )

    async def generate_token(self, config: Optional[dict] = None) -> str:
        config = config or {}
        return await self._request(
            {
                "t": protocol.TOKEN,
                "user_id": config.get("user_id"),
                "workspace": config.get("workspace"),
                "ttl_seconds": config.get("expires_in"),
                "is_admin": config.get("is_admin", False),
            }
        )

    async def ping(self) -> float:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending["__ping__"] = fut
        await self._send_msg({"t": protocol.PING})
        return await asyncio.wait_for(fut, 10.0)


async def connect_to_server(config: dict[str, Any]) -> ServerConnection:
    """hypha-style entry point: ``{"server_url": ..., "token": ...}``.

    Optional transport keys: ``shm_store`` (a store instance for the
    same-host fast path, ``"auto"`` to attach the advertised native
    segment, None to disable) and ``transport_config``."""
    url = config["server_url"]
    if url.startswith("http"):
        url = "ws" + url[4:]
    if not url.endswith("/ws"):
        url = url.rstrip("/") + "/ws"
    conn = ServerConnection(
        url,
        token=config.get("token"),
        timeout=config.get("method_timeout", 300.0),
        shm_store=config.get("shm_store", "auto"),
        transport_config=config.get("transport_config"),
        protocols=config.get("protocols"),
    )
    return await conn.connect()
