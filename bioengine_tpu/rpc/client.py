"""RPC client: connect to a control-plane server, register services,
call remote services.

API shape mirrors what the reference gets from hypha-rpc's
``connect_to_server`` (a server object with register_service /
get_service / generate_token, ref bioengine/worker/worker.py:522-612),
so worker/app code reads the same against our in-repo control plane.
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import Any, Callable, Optional

import aiohttp

from bioengine_tpu.rpc import protocol
from bioengine_tpu.rpc.schema import extract_schema
from bioengine_tpu.rpc.transport import (
    Codec,
    TransportConfig,
    attach_store_by_name,
)
from bioengine_tpu.testing import faults
from bioengine_tpu.utils import flight, tracing
from bioengine_tpu.utils.backoff import full_jitter_delay
from bioengine_tpu.utils.logger import create_logger
from bioengine_tpu.utils.tasks import spawn_supervised


class ConnectionLost(ConnectionError):
    """The websocket dropped with this call in flight. The outcome on
    the server is unknown — the serving layer retries only idempotent
    calls."""


def _expire_request(fut: asyncio.Future) -> None:
    # timer callback for _request: fires only if the RESULT never came
    if not fut.done():
        fut.set_exception(asyncio.TimeoutError())


class ServiceProxy:
    """Callable facade over a remote service: ``await svc.method(...)``."""

    def __init__(self, connection: "ServerConnection", service_info: dict):
        self._connection = connection
        self._info = service_info
        self.id = service_info["id"]

    def __getattr__(self, name: str) -> Callable:
        if name.startswith("_"):
            raise AttributeError(name)

        async def call(*args, **kwargs):
            return await self._connection.call(self.id, name, *args, **kwargs)

        call.__name__ = name
        return call

    def __repr__(self) -> str:
        return f"<ServiceProxy {self.id} methods={self._info.get('methods')}>"


class ServerConnection:
    """A live WebSocket session with the RPC server."""

    def __init__(
        self,
        url: str,
        token: Optional[str] = None,
        timeout: float = 300.0,
        shm_store: Any = "auto",
        transport_config: Optional[TransportConfig] = None,
        protocols: Optional[list[str]] = None,
        auto_reconnect: bool = False,
        reconnect_max_backoff_s: float = 5.0,
        compat_pre_fast1: bool = False,
    ):
        self.url = url
        # same-host deployments skip the TCP stack entirely:
        # ``unix:///path/to.sock`` dials the server's unix-domain
        # listener — ~40% lower per-message syscall cost on the
        # small-request hot path (docs/performance.md)
        self._uds_path: Optional[str] = (
            url[len("unix://"):] if url.startswith("unix://") else None
        )
        self.token = token
        self.timeout = timeout
        # capabilities declared at handshake; [] forces pure-legacy
        # framing in BOTH directions (bench baseline, interop tests)
        self.protocols = (
            [
                protocol.PROTO_OOB1,
                protocol.PROTO_TRACE1,
                protocol.PROTO_TELEM1,
                protocol.PROTO_MESH1,
                protocol.PROTO_EPOCH1,
                protocol.PROTO_FAST1,
                protocol.PROTO_STREAM1,
            ]
            if protocols is None
            else list(protocols)
        )
        # what the SERVER advertised at the last welcome (telem1 and
        # future server-side capabilities gate on this, see
        # peer_supports) and the last measured wall-clock offset to it
        self.peer_protocols: list[str] = []
        # the controller fencing epoch the server's welcome advertised
        # (None on legacy / non-controller servers) — worker hosts use
        # it to refuse rejoining a stale revived controller
        self.peer_epoch: Optional[int] = None
        self.clock_offset_s: Optional[float] = None
        self.clock_offset_rtt_s: Optional[float] = None
        self.auto_reconnect = auto_reconnect
        self.reconnect_max_backoff_s = reconnect_max_backoff_s
        # connection-lifecycle hooks (sync or async callables): fired on
        # an UNEXPECTED drop, and after a successful re-establish +
        # service re-registration respectively
        self.on_disconnect: list[Callable[[], Any]] = []
        self.on_reconnect: list[Callable[[], Any]] = []
        self.client_id: Optional[str] = None
        self.workspace: Optional[str] = None
        self.user_id: Optional[str] = None
        self.logger = create_logger("rpc.client", log_file="off")
        self._session: Optional[aiohttp.ClientSession] = None
        self._ws: Optional[aiohttp.ClientWebSocketResponse] = None
        self._pending: dict[str, asyncio.Future] = {}
        # open streaming calls: call_id -> queue of ("item", seq, value)
        # / ("end", count, spans) / ("err", 0, exc) — fed by the read
        # loop, drained by call_stream
        self._streams: dict[str, asyncio.Queue] = {}
        # call ids need per-connection uniqueness, not global entropy:
        # one random prefix at construction, then a counter — minting
        # 64 random bits per request shows up on the microsecond path
        self._call_prefix = f"{tracing.new_id()[:8]}-"
        self._call_seq = 0
        # measurement compat: reproduce the pre-fast1 per-request
        # bookkeeping (a fresh uuid call id + an asyncio.wait_for
        # timeout chain per call) so the request_overhead bench's
        # baseline leg measures the pre-optimization stack in the SAME
        # interpreter as the fast leg. Never set on production paths.
        self._compat_request = compat_pre_fast1
        self._local_services: dict[str, dict[str, Callable]] = {}
        self._service_definitions: dict[str, dict[str, Any]] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._closing = False
        self._reconnect_task: Optional[asyncio.Task] = None
        self.codec = Codec(config=transport_config or TransportConfig.from_env())
        self._shm_store_cfg = shm_store
        self._owns_shm = False

    async def connect(self) -> "ServerConnection":
        await self._establish()
        return self

    async def _establish(self) -> None:
        """One transport bring-up: websocket + welcome + reader + shm
        negotiation. Shared by ``connect`` and the reconnect loop."""
        await self._teardown_transport()
        if self._uds_path is not None:
            self._session = aiohttp.ClientSession(
                connector=aiohttp.UnixConnector(path=self._uds_path)
            )
            # the connector owns routing; the authority is synthetic
            url = "ws://localhost/ws"
        else:
            self._session = aiohttp.ClientSession()
            url = self.url
        # declare codec support at handshake; a pre-oob server ignores
        # unknown query params and its welcome carries no "protocols",
        # so both sides settle on legacy frames automatically
        if self.protocols:
            sep = "&" if "?" in url else "?"
            url = f"{url}{sep}proto={','.join(self.protocols)}"
        if self.token:
            sep = "&" if "?" in url else "?"
            url = f"{url}{sep}token={self.token}"
        self._ws = await self._session.ws_connect(
            url, max_msg_size=self.codec.config.max_msg_size
        )
        welcome = self.codec.decode((await self._ws.receive()).data)
        self.client_id = welcome["client_id"]
        self.workspace = welcome["workspace"]
        self.user_id = welcome["user_id"]
        self.peer_protocols = list(welcome.get("protocols", []))
        self.peer_epoch = welcome.get("epoch")
        self.codec.oob = protocol.PROTO_OOB1 in self.protocols and (
            protocol.PROTO_OOB1 in welcome.get("protocols", [])
        )
        # trace fields ride the CALL envelope only when BOTH sides
        # advertise trace1 — a legacy peer never sees them on the wire
        self.codec.trace = protocol.PROTO_TRACE1 in self.protocols and (
            protocol.PROTO_TRACE1 in welcome.get("protocols", [])
        )
        # BEFS small-request frames, same both-sides rule as oob1 —
        # a legacy peer keeps seeing byte-identical legacy frames
        self.codec.fast = protocol.PROTO_FAST1 in self.protocols and (
            protocol.PROTO_FAST1 in welcome.get("protocols", [])
        )
        self._reader_task = asyncio.create_task(self._read_loop())
        if self.codec.oob and isinstance(welcome.get("shm"), dict):
            await self._negotiate_shm(welcome["shm"])

    async def _teardown_transport(self) -> None:
        """Close ws/session remnants without touching pending futures
        or service state (reconnect keeps both)."""
        if self._reader_task and self._reader_task is not asyncio.current_task():
            self._reader_task.cancel()
            self._reader_task = None
        if self._ws is not None and not self._ws.closed:
            try:
                await self._ws.close()
            except Exception as e:  # noqa: BLE001 — remnant of a dead transport
                self.logger.debug(f"stale ws close raised: {e}")
        if self._session is not None:
            try:
                await self._session.close()
            except Exception as e:  # noqa: BLE001 — remnant of a dead transport
                self.logger.debug(f"stale session close raised: {e}")
        self._ws = None
        self._session = None

    async def _negotiate_shm(self, offer: dict) -> None:
        """Same-host handshake: map the server's segment, read the
        probe nonce out of it, echo it back. Any failure leaves the
        connection on wire frames — never fatal."""
        store = self._shm_store_cfg
        if store == "auto":
            # first probe may build the native lib (subprocess cc) —
            # keep the handshake off the loop's critical path
            store = await asyncio.to_thread(
                attach_store_by_name, offer.get("name", "")
            )
            self._owns_shm = store is not None
        if store is None:
            return
        try:
            nonce = store.get_bytes(offer["probe_key"])
        except Exception:  # noqa: BLE001 — foreign/mismatched segment
            nonce = None
        if nonce is None:
            if self._owns_shm:
                store.close()
                self._owns_shm = False
            return
        verified = await self._request(
            {"t": protocol.SHM_ACK, "nonce": nonce}
        )
        if verified:
            self.codec.enable_shm(store)
            self.logger.info("shm fast path negotiated")
        elif self._owns_shm:
            store.close()
            self._owns_shm = False

    async def disconnect(self) -> None:
        self._closing = True
        if self._reconnect_task is not None:
            self._reconnect_task.cancel()
            self._reconnect_task = None
        if self._reader_task:
            self._reader_task.cancel()
        if self._ws:
            await self._ws.close()
        if self._session:
            await self._session.close()
        self._fail_inflight(ConnectionLost("client disconnected"))
        shm = self.codec.shm_store
        self.codec.close()
        if shm is not None and self._owns_shm:
            shm.close()

    def describe(self) -> dict:
        """Data-plane counters for this connection (mirrors
        RpcServer.describe)."""
        return {
            "url": self.url,
            "connected": self.connected,
            "oob": self.codec.oob,
            "fast": self.codec.fast,
            "shm": self.codec.shm_store.name
            if self.codec.shm_store is not None
            else None,
            "transport": self.codec.stats.as_dict(),
        }

    @property
    def connected(self) -> bool:
        return self._ws is not None and not self._ws.closed

    # ---- request/response ---------------------------------------------------

    async def _read_loop(self) -> None:
        assert self._ws is not None
        try:
            async for msg in self._ws:
                if msg.type != aiohttp.WSMsgType.BINARY:
                    continue
                raw = msg.data
                try:
                    if protocol.is_fast_frame(raw):
                        # BEFS: sync decode, no pins to drain. A
                        # RESULT resolves its future straight from the
                        # (call_id, value) parse — fast frames can
                        # never carry spans or errors, so the generic
                        # handling below has nothing to add
                        parsed = self.codec.decode_fast_result_frame(raw)
                        if parsed is not None:
                            fut = self._pending.pop(parsed[0], None)
                            if fut is not None and not fut.done():
                                fut.set_result(parsed[1])
                            elif parsed[0] in self._streams:
                                # closing RESULT of a streaming call:
                                # fast result frames carry no spans
                                self._streams[parsed[0]].put_nowait(
                                    ("end", parsed[1], None)
                                )
                            continue
                        sparsed = self.codec.decode_fast_stream_frame(raw)
                        if sparsed is not None:
                            q = self._streams.get(sparsed[0])
                            if q is not None:
                                q.put_nowait(("item", sparsed[1], sparsed[2]))
                            continue
                        data = self.codec.decode_fast_frame(raw)
                    else:
                        try:
                            data = await self.codec.decode_async(raw)
                        finally:
                            # retry releasing pins of earlier shm
                            # payloads whose consumers have since
                            # dropped their views (results are handed
                            # to caller futures, so the release point
                            # is only observable opportunistically)
                            self.codec.drain_pins()
                except Exception as e:  # noqa: BLE001
                    # a poisoned message (e.g. its shm object was
                    # evicted before we consumed it) must cost only
                    # that message — the affected call times out, the
                    # connection and every other in-flight call live
                    self.logger.error(f"dropping undecodable message: {e}")
                    continue
                if data is None:
                    continue  # mid-reassembly chunk
                t = data.get("t")
                if t in (protocol.RESULT, protocol.ERROR):
                    if data.get("spans"):
                        # sampled-trace spans recorded by the peer while
                        # serving our call — fold into the local buffer
                        # so one process holds the whole tree
                        tracing.absorb_spans(data["spans"])
                    call_id = data.get("call_id", "")
                    fut = self._pending.pop(call_id, None)
                    if fut and not fut.done():
                        if t == protocol.RESULT:
                            fut.set_result(data.get("result"))
                        else:
                            err = data.get("error")
                            if not isinstance(err, Exception):
                                err = RuntimeError(str(err))
                            fut.set_exception(err)
                    elif call_id in self._streams:
                        q = self._streams[call_id]
                        if t == protocol.RESULT:
                            q.put_nowait(("end", data.get("result"), None))
                        else:
                            err = data.get("error")
                            if not isinstance(err, Exception):
                                err = RuntimeError(str(err))
                            q.put_nowait(("err", 0, err))
                elif t == protocol.STREAM:
                    q = self._streams.get(data.get("call_id", ""))
                    if q is not None:
                        q.put_nowait(
                            ("item", data.get("seq", 0), data.get("item"))
                        )
                elif t == protocol.CALL:
                    spawn_supervised(
                        self._handle_incoming_call(data),
                        name="rpc-incoming-call",
                        logger=self.logger,
                    )
                elif t == protocol.PONG:
                    fut = self._pending.pop("__ping__", None)
                    if fut and not fut.done():
                        fut.set_result(data.get("ts"))
        except asyncio.CancelledError:
            return
        except Exception as e:  # noqa: BLE001 — transport died under us
            self.logger.error(f"read loop failed: {e}")
        # the websocket closed without disconnect(): classify every
        # in-flight future NOW (a caller must see a typed transport
        # error immediately, not a timeout), then heal if configured
        self._on_connection_lost()

    def _on_connection_lost(self) -> None:
        if self._closing:
            return
        self.logger.warning("connection to server lost")
        flight.record(
            "client.disconnect",
            severity="warning",
            url=self.url,
            client_id=self.client_id,
            in_flight=len(self._pending),
        )
        self._fail_inflight(
            ConnectionLost(f"connection to {self.url} lost mid-call")
        )
        for cb in self.on_disconnect:
            try:
                result = cb()
                if asyncio.iscoroutine(result):
                    spawn_supervised(
                        result, name="rpc-on-disconnect", logger=self.logger
                    )
            except Exception as e:  # noqa: BLE001 — hooks never kill the client
                self.logger.error(f"on_disconnect callback failed: {e}")
        if self.auto_reconnect and (
            self._reconnect_task is None or self._reconnect_task.done()
        ):
            # exactly one reconnect loop at a time: a re-drop while a
            # loop is mid-retry must not spawn a second one (each
            # _establish tears down the transport — two racing loops
            # would keep closing each other's fresh connection)
            self._reconnect_task = spawn_supervised(
                self._reconnect_loop(),
                name="rpc-reconnect",
                logger=self.logger,
            )

    def _fail_inflight(self, exc: Exception) -> None:
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)
                # a caller that already bailed (e.g. its send raised
                # first) never awaits this future — mark the exception
                # retrieved so the loop doesn't report it at GC time
                fut.exception()
        # open streams see the SAME typed transport error as unary
        # calls — the serving layer's idempotent-failover rules key on
        # ConnectionLost, streams included
        streams, self._streams = self._streams, {}
        for q in streams.values():
            q.put_nowait(("err", 0, exc))

    async def _reconnect_loop(self) -> None:
        """Re-establish with exponential backoff + full jitter, then
        re-register every local service and fire ``on_reconnect``."""
        attempt = 0
        while not self._closing:
            await asyncio.sleep(
                full_jitter_delay(attempt, 0.2, self.reconnect_max_backoff_s)
            )
            attempt += 1
            try:
                await self._establish()
                await self._reregister_services()
                for cb in self.on_reconnect:
                    result = cb()
                    if asyncio.iscoroutine(result):
                        await result
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — keep trying
                self.logger.warning(
                    f"reconnect attempt {attempt} failed: {e}"
                )
                continue
            self.logger.info(f"reconnected after {attempt} attempt(s)")
            flight.record(
                "client.reconnect",
                url=self.url,
                client_id=self.client_id,
                attempts=attempt,
            )
            return

    async def _reregister_services(self) -> None:
        # one registration implementation: register_service rebuilds the
        # wire definition and refreshes both local maps
        for definition in list(self._service_definitions.values()):
            await self.register_service(definition)

    async def _send_msg(self, msg: dict) -> None:
        if faults.ACTIVE:
            await faults.hit("rpc.client.send", drop=self._abort_connection)
        ws = self._ws
        if ws is None or ws.closed:
            raise ConnectionLost("rpc connection is down")
        codec = self.codec
        if codec.fast:
            # small-request hot path: one sync encode attempt, one
            # send — skips the encode_frames_async coroutine and the
            # payload-size walk entirely when it hits
            frame = codec.encode_fast_frame(msg)
            if frame is not None:
                await ws.send_bytes(frame)
                return
        for frame in await codec.encode_frames_async(msg):
            await ws.send_bytes(frame)

    async def _send_stream_item(self, call_id: str, seq: int, item: Any) -> None:
        """One stream item to the server. Per-token sends are THE hot
        path of a generation — try the BEFS stream frame first and only
        build the STREAM envelope dict on fallback (mirrors
        ``_request_fast``'s inlined send)."""
        if faults.ACTIVE:
            await faults.hit("rpc.client.send", drop=self._abort_connection)
        ws = self._ws
        if ws is None or ws.closed:
            raise ConnectionLost("rpc connection is down")
        codec = self.codec
        if codec.fast:
            frame = codec.encode_fast_stream_frame(call_id, seq, item)
            if frame is not None:
                await ws.send_bytes(frame)
                return
        for frame in await codec.encode_frames_async(
            {"t": protocol.STREAM, "call_id": call_id, "seq": seq, "item": item}
        ):
            await ws.send_bytes(frame)

    async def _abort_connection(self) -> None:
        """Sever the transport WITHOUT the closing handshake semantics
        of disconnect() — the fault-injection analog of a network
        partition; the read loop notices and runs the lost-connection
        path (in-flight failure + reconnect)."""
        if self._ws is not None and not self._ws.closed:
            await self._ws.close()

    async def _request(self, msg: dict) -> Any:
        if self._compat_request:
            # pre-fast1 request path, kept verbatim for the bench's
            # baseline leg (see compat_pre_fast1 in __init__)
            msg["call_id"] = call_id = tracing.new_id()
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[call_id] = fut
            try:
                await self._send_msg(msg)
                return await asyncio.wait_for(fut, self.timeout)
            finally:
                self._pending.pop(call_id, None)
        self._call_seq = seq = self._call_seq + 1
        msg["call_id"] = call_id = f"{self._call_prefix}{seq:x}"
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending[call_id] = fut
        try:
            await self._send_msg(msg)
            # a bare timer handle, not asyncio.wait_for: wait_for
            # allocates an extra future + callback chain per call —
            # measurable on the small-request path. Semantics match:
            # TimeoutError after self.timeout, cancelled on exit.
            timer = loop.call_later(self.timeout, _expire_request, fut)
            try:
                return await fut
            finally:
                timer.cancel()
        finally:
            # RESULT/ERROR pop on arrival; this covers timeout/cancel so
            # abandoned futures don't accumulate across reconnects
            self._pending.pop(call_id, None)

    async def _handle_incoming_call(self, msg: dict) -> None:
        """The server is routing another client's call to one of OUR
        registered services. A sampled trace context on the CALL is
        activated around the handler (local spans chain under the
        caller's span) and the spans it closes ship back on the
        RESULT/ERROR frame."""
        assert self._ws is not None
        ctx = token = None
        if self.codec.trace and isinstance(msg.get("trace"), dict):
            ctx = tracing.TraceContext.from_wire(msg["trace"])
            token = tracing.activate(ctx)

        def _spans() -> dict:
            if ctx is not None and ctx.collector:
                return {"spans": ctx.collector}
            return {}

        try:
            service = self._local_services[msg["service_id"]]
            fn = service[msg["method"]]
            with (
                tracing.span(
                    "rpc.handle",
                    service=msg["service_id"],
                    method=msg["method"],
                )
                if tracing.sampled()
                else tracing.NOOP_SPAN
            ):
                result = fn(*msg.get("args", []), **msg.get("kwargs", {}))
                if asyncio.iscoroutine(result):
                    result = await result
            if hasattr(result, "__aiter__"):
                if msg.get("stream"):
                    # streaming handler for a streaming caller: one
                    # STREAM frame per item (fast-encoded when small),
                    # closed by a RESULT carrying the item count so the
                    # caller can detect truncation
                    seq = 0
                    try:
                        async for item in result:
                            await self._send_stream_item(
                                msg.get("call_id"), seq, item
                            )
                            seq += 1
                    except BaseException:
                        # a failed send mid-stream must not leave the
                        # provider's generator suspended until GC — its
                        # finally blocks release decode slots / ongoing
                        # counts, so close it deterministically
                        with contextlib.suppress(Exception):
                            await result.aclose()
                        raise
                    result = {"n": seq}
                else:
                    # legacy caller on a streaming method: drain to a
                    # list so the method stays callable without stream1
                    result = [item async for item in result]
            await self._send_msg(
                {
                    "t": protocol.RESULT,
                    "call_id": msg.get("call_id"),
                    "result": result,
                    **_spans(),
                }
            )
        except Exception as e:
            await self._send_msg(
                {
                    "t": protocol.ERROR,
                    "call_id": msg.get("call_id"),
                    "error": e,
                    **_spans(),
                }
            )
        finally:
            if token is not None:
                tracing.deactivate(token)
            # args decoded from shm refs die with the handler — let the
            # store reclaim their blocks
            self.codec.drain_pins()

    # ---- public API (hypha-shaped) ------------------------------------------

    async def register_service(self, definition: dict[str, Any]) -> dict:
        methods = {k: v for k, v in definition.items() if callable(v)}
        schemas = {
            k: getattr(v, "__schema__", extract_schema(v))
            for k, v in methods.items()
        }
        wire_def = {k: v for k, v in definition.items() if not callable(v)}
        wire_def["methods"] = schemas
        result = await self._request(
            {"t": protocol.REGISTER, "definition": wire_def}
        )
        full_id = result["id"]
        self._local_services[full_id] = methods
        # remember the ORIGINAL definition (with callables) so a
        # reconnect can re-register this service transparently
        self._service_definitions[full_id] = dict(definition)
        return {"id": full_id}

    async def unregister_service(self, service_id: str) -> None:
        await self._request(
            {"t": protocol.UNREGISTER, "service_id": service_id}
        )
        self._local_services.pop(service_id, None)
        self._service_definitions.pop(service_id, None)

    async def list_services(self, workspace: Optional[str] = None) -> list[dict]:
        return await self._request(
            {"t": protocol.LIST, "workspace": workspace}
        )

    async def get_service(self, service_id: str) -> ServiceProxy:
        services = await self.list_services()
        for info in services:
            if info["id"] == service_id or info["id"].endswith(f"/{service_id}"):
                return ServiceProxy(self, info)
        raise KeyError(f"Service '{service_id}' not found")

    async def call(self, service_id: str, method: str, *args, **kwargs) -> Any:
        codec = self.codec
        ctx = tracing.current_trace()
        traced = codec.trace and ctx is not None and ctx.sampled
        if codec.fast and not traced and not self._compat_request:
            # small-request hot path: encode straight from the call
            # site — the envelope dict is only built if the fast
            # encode bails (oversize / non-scalar payload)
            return await self._request_fast(service_id, method, args, kwargs)
        msg = {
            "t": protocol.CALL,
            "service_id": service_id,
            "method": method,
            "args": list(args),
            "kwargs": kwargs,
        }
        if traced:
            msg["trace"] = ctx.to_wire()
        return await self._request(msg)

    async def call_stream(
        self,
        service_id: str,
        method: str,
        *args,
        item_timeout: Optional[float] = None,
        **kwargs,
    ):
        """Call a streaming service method; async-iterates its items.

        The CALL carries ``stream: True``; the provider sends one
        STREAM frame per item and closes with a counting RESULT. A
        per-item inactivity timeout (default: the connection timeout)
        replaces the unary whole-call timer — a healthy generation may
        run far longer than any single gap between tokens. Out-of-order
        or missing items raise :class:`ConnectionLost` (the transport
        guarantees ordering, so a gap means frames were lost to a drop
        mid-stream)."""
        if self.peer_protocols and not self.peer_supports(protocol.PROTO_STREAM1):
            raise RuntimeError(
                "server does not support streaming calls (stream1)"
            )
        self._call_seq = seq = self._call_seq + 1
        call_id = f"{self._call_prefix}{seq:x}"
        q: asyncio.Queue = asyncio.Queue()
        self._streams[call_id] = q
        msg: dict[str, Any] = {
            "t": protocol.CALL,
            "call_id": call_id,
            "service_id": service_id,
            "method": method,
            "args": list(args),
            "kwargs": kwargs,
            "stream": True,
        }
        ctx = tracing.current_trace()
        if self.codec.trace and ctx is not None and ctx.sampled:
            msg["trace"] = ctx.to_wire()
        gap = item_timeout if item_timeout is not None else self.timeout
        expected = 0
        try:
            await self._send_msg(msg)
            while True:
                kind, a, b = await asyncio.wait_for(q.get(), gap)
                if kind == "item":
                    if a != expected:
                        raise ConnectionLost(
                            f"stream {call_id} gap: expected item "
                            f"{expected}, got {a}"
                        )
                    expected += 1
                    yield b
                elif kind == "end":
                    n = a.get("n") if isinstance(a, dict) else None
                    if n is not None and n != expected:
                        raise ConnectionLost(
                            f"stream {call_id} truncated: provider sent "
                            f"{n} items, received {expected}"
                        )
                    return
                else:
                    raise b
        finally:
            self._streams.pop(call_id, None)

    async def _request_fast(
        self, service_id: str, method: str, args: tuple, kwargs: dict
    ) -> Any:
        self._call_seq = seq = self._call_seq + 1
        call_id = f"{self._call_prefix}{seq:x}"
        frame = self.codec.encode_fast_call_frame(
            call_id, service_id, method, args, kwargs
        )
        if frame is None:
            return await self._request(
                {
                    "t": protocol.CALL,
                    "service_id": service_id,
                    "method": method,
                    "args": list(args),
                    "kwargs": kwargs,
                }
            )
        # inlined _send_msg minus the encode (already done): one fault
        # gate, one liveness check, one send
        if faults.ACTIVE:
            await faults.hit("rpc.client.send", drop=self._abort_connection)
        ws = self._ws
        if ws is None or ws.closed:
            raise ConnectionLost("rpc connection is down")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending[call_id] = fut
        try:
            await ws.send_bytes(frame)
            timer = loop.call_later(self.timeout, _expire_request, fut)
            try:
                return await fut
            finally:
                timer.cancel()
        finally:
            self._pending.pop(call_id, None)

    async def generate_token(self, config: Optional[dict] = None) -> str:
        config = config or {}
        return await self._request(
            {
                "t": protocol.TOKEN,
                "user_id": config.get("user_id"),
                "workspace": config.get("workspace"),
                "ttl_seconds": config.get("expires_in"),
                "is_admin": config.get("is_admin", False),
            }
        )

    async def ping(self) -> float:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending["__ping__"] = fut
        await self._send_msg({"t": protocol.PING})
        return await asyncio.wait_for(fut, 10.0)

    def peer_supports(self, capability: str) -> bool:
        """Did the server advertise ``capability`` at the welcome?
        (Client-declared capabilities gate what WE put on the wire;
        this gates what we may ASK of the server — e.g. ``telem1``'s
        push_telemetry verb.)"""
        return capability in self.peer_protocols

    async def measure_clock_offset(self, samples: int = 3) -> dict:
        """Estimate this process's wall-clock offset to the server via
        RTT-midpoint pings (NTP's core idea): the server's PONG
        timestamp is assumed taken halfway through the round trip, so
        ``offset = server_ts - (t_send + t_recv)/2``. The sample with
        the smallest RTT wins — queueing delay only ever inflates RTT,
        and the least-delayed exchange is closest to the symmetric
        ideal. Stored on the connection (``clock_offset_s``, positive =
        the server's clock is ahead of ours) and refreshed by callers
        on reconnect; merged incident timelines use it to de-skew
        multi-host event ordering (utils/flight.merge_records)."""
        import time as _time

        best: Optional[tuple[float, float]] = None  # (rtt, offset)
        for _ in range(max(1, samples)):
            t0 = _time.time()
            server_ts = await self.ping()
            t1 = _time.time()
            rtt = t1 - t0
            offset = float(server_ts) - (t0 + t1) / 2.0
            if best is None or rtt < best[0]:
                best = (rtt, offset)
        self.clock_offset_rtt_s, self.clock_offset_s = best
        return {
            "offset_s": round(best[1], 6),
            "rtt_s": round(best[0], 6),
            "samples": samples,
        }


async def connect_to_server(config: dict[str, Any]) -> ServerConnection:
    """hypha-style entry point: ``{"server_url": ..., "token": ...}``.

    Optional transport keys: ``shm_store`` (a store instance for the
    same-host fast path, ``"auto"`` to attach the advertised native
    segment, None to disable), ``transport_config``, and ``reconnect``
    (auto-reconnect with backoff on an unexpected drop; registered
    services are re-registered transparently)."""
    url = config["server_url"]
    if url.startswith("unix://"):
        pass  # a socket path, not an authority — used verbatim
    else:
        if url.startswith("http"):
            url = "ws" + url[4:]
        if not url.endswith("/ws"):
            url = url.rstrip("/") + "/ws"
    conn = ServerConnection(
        url,
        token=config.get("token"),
        timeout=config.get("method_timeout", 300.0),
        shm_store=config.get("shm_store", "auto"),
        transport_config=config.get("transport_config"),
        protocols=config.get("protocols"),
        auto_reconnect=bool(config.get("reconnect", False)),
        compat_pre_fast1=bool(config.get("compat_pre_fast1", False)),
    )
    return await conn.connect()
