"""Transport plumbing for the zero-copy RPC data plane.

``protocol.py`` owns the byte format; this module owns everything a
live connection needs around it:

- ``TransportConfig`` — env-tunable thresholds (chunk size, shm
  threshold, off-loop offload threshold).
- ``RpcStats`` — bytes/frames/chunks in+out, encode/decode seconds,
  shm hit/fallback counters; surfaced by ``RpcServer.describe`` /
  ``ServerConnection.describe`` and the worker status dict.
- ``chunk_frames``/``FrameAssembler`` — oversized frames split into
  ``BEC1`` chunks at ``frame_limit`` and reassembled on the receive
  side, replacing the old hard 256 MB ``max_msg_size`` ceiling with a
  bounded per-websocket-message size (chunk streams from concurrent
  sends may interleave; reassembly is keyed by message id).
- ``ShmPinTracker`` — store pins taken while decoding shm refs, held
  until the consumer drops its array views, then released+deleted.
- ``Codec`` — one per connection: negotiated capabilities (oob,
  shm store), encode-to-frames / decode-from-frames, and off-loop
  execution of both above ``offload_threshold`` so a 64 MB payload
  never serializes on the asyncio event loop (the exact blocking
  pattern BE-ASYNC-001 exists to catch).
"""

from __future__ import annotations

import asyncio
import os
import secrets
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

import msgpack

from bioengine_tpu.rpc import protocol
from bioengine_tpu.utils import metrics

# module-level bind: a global-name load beats two attribute hops on a
# function called four times per small-request round trip
_perf_counter = time.perf_counter


def _env_mb(name: str, default_mb: float) -> int:
    return int(float(os.environ.get(name, default_mb)) * 1024 * 1024)


@dataclass
class TransportConfig:
    # one websocket message never exceeds this; larger frames chunk.
    # 128 MB keeps every realistic tensor message single-frame (no
    # chunk-join copy) while chunking still removes the old 256 MB
    # ceiling for the giants
    frame_limit: int = 128 * 1024 * 1024
    # buffers at least this large go through the shared store when a
    # same-host segment is negotiated
    shm_threshold: int = 1024 * 1024
    # encode/decode with more payload than this run off-loop
    offload_threshold: int = 4 * 1024 * 1024
    # receive-side ceiling for ONE websocket message — covers our own
    # chunks (frame_limit + header) and unchunked legacy-peer sends
    # (their encoder caps out where the old wire cap sat)
    max_msg_size: int = 256 * 1024 * 1024
    # ceiling for ONE reassembled logical message: chunking removes
    # the per-websocket-message cap, so this is the replacement bound
    # on what a peer's chunk headers can make the receiver allocate
    max_assembled: int = 2 * 1024 * 1024 * 1024
    # whole-frame byte ceiling for BEFS small-request fast frames; a
    # message that packs larger falls back to the full codec
    fast_threshold: int = protocol.FAST_THRESHOLD_DEFAULT

    def __post_init__(self) -> None:
        # a chunk (frame_limit payload + ~64-byte header) must fit the
        # receiver's per-websocket-message cap, or every chunked send
        # would kill the connection — clamp rather than trusting two
        # independently-tunable env vars to agree
        self.frame_limit = max(
            min(self.frame_limit, self.max_msg_size - 65536), 65536
        )

    @classmethod
    def from_env(cls) -> "TransportConfig":
        return cls(
            frame_limit=_env_mb("BIOENGINE_RPC_FRAME_LIMIT_MB", 128),
            shm_threshold=_env_mb("BIOENGINE_RPC_SHM_THRESHOLD_MB", 1),
            offload_threshold=_env_mb("BIOENGINE_RPC_OFFLOAD_MB", 4),
            max_msg_size=_env_mb("BIOENGINE_RPC_MAX_MSG_MB", 256),
            max_assembled=_env_mb("BIOENGINE_RPC_MAX_ASSEMBLED_MB", 2048),
            fast_threshold=int(
                float(
                    os.environ.get(
                        "BIOENGINE_RPC_FAST_THRESHOLD",
                        protocol.FAST_THRESHOLD_DEFAULT,
                    )
                )
            ),
        )


@dataclass(eq=False)  # identity semantics — instances live in a WeakSet
class RpcStats:
    """Data-plane counters for one server or one client connection.

    Mutations hold ``lock``: encode/decode above the offload threshold
    run in ``asyncio.to_thread`` workers, concurrently across clients
    — unlocked ``+=`` would silently drop increments exactly under the
    high-throughput conditions the counters exist to observe."""

    lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )
    bytes_out: int = 0
    bytes_in: int = 0
    msgs_out: int = 0
    msgs_in: int = 0
    frames_out: int = 0
    frames_in: int = 0
    chunked_msgs_out: int = 0
    chunked_msgs_in: int = 0
    encode_seconds: float = 0.0
    decode_seconds: float = 0.0
    shm_puts: int = 0
    shm_put_bytes: int = 0
    shm_gets: int = 0
    shm_get_bytes: int = 0
    shm_fallbacks: int = 0       # store absent/full -> wire frame
    legacy_msgs_out: int = 0     # peers without oob1
    # payloads extracted into BEF1 scatter-gather tables on encode (the
    # wire half of the zero-copy path; shm_puts is the same-host half).
    # What the cross-host mesh tests PIN: activation arrays between
    # shards must land here, never as legacy inline double-packs.
    oob_payloads_out: int = 0
    oob_payload_bytes_out: int = 0
    # BEFS small-request fast frames. fast_fallbacks counts CALL/RESULT
    # envelopes on a fast1 connection that still needed the full codec
    # (trace attached, spans piggybacked, oversize or non-scalar args)
    # — the hit-rate denominator next to shm_hit_rate.
    small_frames_out: int = 0
    small_frames_in: int = 0
    fast_fallbacks: int = 0

    def __post_init__(self) -> None:
        # every live stats object feeds the process-wide metrics plane
        # at scrape time (utils/metrics.py collector) — describe() and
        # GET /metrics read the SAME counters, no double bookkeeping
        _STATS_INSTANCES.add(self)

    def as_dict(self) -> dict:
        with self.lock:
            d = dict(self.__dict__)
        d.pop("lock", None)
        d["encode_seconds"] = round(d["encode_seconds"], 4)
        d["decode_seconds"] = round(d["decode_seconds"], 4)
        shm_total = d["shm_puts"] + d["shm_fallbacks"]
        d["shm_hit_rate"] = (
            round(d["shm_puts"] / shm_total, 4) if shm_total else None
        )
        fast_total = d["small_frames_out"] + d["fast_fallbacks"]
        d["fast_frame_hit_rate"] = (
            round(d["small_frames_out"] / fast_total, 4)
            if fast_total
            else None
        )
        return d


_RPC_METRIC_FIELDS = (
    "bytes_out", "bytes_in", "msgs_out", "msgs_in", "frames_out",
    "frames_in", "chunked_msgs_out", "chunked_msgs_in", "encode_seconds",
    "decode_seconds", "shm_puts", "shm_put_bytes", "shm_gets",
    "shm_get_bytes", "shm_fallbacks", "legacy_msgs_out",
    "oob_payloads_out", "oob_payload_bytes_out",
    "small_frames_out", "small_frames_in", "fast_fallbacks",
)


def _collect_rpc_stats(instances: list) -> list:
    """Fold every live RpcStats (server + each client connection in
    this process) into process totals. Per-connection breakdowns stay
    on describe(); the metrics plane wants the aggregate an autoscaler
    or dashboard keys on."""
    totals = dict.fromkeys(_RPC_METRIC_FIELDS, 0.0)
    for st in instances:
        with st.lock:
            for f in _RPC_METRIC_FIELDS:
                totals[f] += getattr(st, f)
    samples = [
        metrics.Sample(
            f"rpc_{name}",
            round(value, 4),
            kind="counter",
            help=f"RPC transport {name.replace('_', ' ')} (process total)",
        )
        for name, value in totals.items()
    ]
    samples.append(
        metrics.Sample(
            "rpc_stats_instances",
            len(instances),
            kind="gauge",
            help="live RpcStats objects (server + client connections)",
        )
    )
    small_total = totals["small_frames_out"] + totals["small_frames_in"]
    samples.append(
        metrics.Sample(
            "rpc_small_frames_total",
            round(small_total, 4),
            kind="counter",
            help="BEFS fast frames on the wire, both directions "
            "(process total)",
        )
    )
    fast_attempts = totals["small_frames_out"] + totals["fast_fallbacks"]
    samples.append(
        metrics.Sample(
            "rpc_fast_frame_hit_rate",
            round(totals["small_frames_out"] / fast_attempts, 4)
            if fast_attempts
            else 0.0,
            kind="gauge",
            help="fraction of fast1 CALL/RESULT envelopes that rode a "
            "BEFS frame instead of the full codec",
        )
    )
    return samples


_STATS_INSTANCES = metrics.InstanceSet("rpc_transport", _collect_rpc_stats)


def chunk_frames(frame, frame_limit: int) -> list:
    """Split ``frame`` into self-describing BEC1 chunks of at most
    ``frame_limit`` payload bytes. A frame that fits returns as-is
    (zero overhead for the common case)."""
    total = len(frame)
    if total <= frame_limit:
        return [frame]
    mv = memoryview(frame)
    msg_id = secrets.token_bytes(8)
    n = (total + frame_limit - 1) // frame_limit
    out = []
    for seq in range(n):
        off = seq * frame_limit
        # "c" (the fixed chunk stride) lets the receiver VALIDATE that
        # offset, seq, and count are mutually consistent — a chunk
        # stream cannot claim coverage it doesn't deliver
        hdr = msgpack.packb(
            {"id": msg_id, "q": seq, "n": n, "z": total, "o": off,
             "c": frame_limit}
        )
        out.append(
            b"".join(
                [
                    protocol.CHUNK_MAGIC,
                    len(hdr).to_bytes(4, "little"),
                    hdr,
                    mv[off : off + frame_limit],
                ]
            )
        )
    return out


class FrameAssembler:
    """Reassembles BEC1 chunk streams into complete frames.

    ``feed`` returns the complete frame (the original bytes for
    unchunked messages) or None while a chunked message is still in
    flight. Interleaved chunk streams are fine — state is per
    message id.

    Chunk headers are peer-controlled, so they are validated before a
    single byte is allocated: the claimed total is capped by
    ``max_assembled`` (the replacement for the per-websocket-message
    bound that chunking removed, which also bounds the SUM of all
    in-flight partial buffers), the fixed chunk stride ``c`` must tie
    offset, seq, and count together (a stream cannot claim coverage it
    doesn't deliver — completion means every byte position was
    written), and a changed header mid-stream is an error. Partial
    streams whose sender went silent expire after ``stale_after``
    seconds so an abandoned transfer cannot pin its buffer forever.

    Completed frames are returned as READ-ONLY memoryviews so decoded
    arrays carry the same immutable contract as unchunked messages
    (aiohttp delivers those as ``bytes``)."""

    def __init__(
        self, max_assembled: int = 2 * 1024 * 1024 * 1024,
        stale_after: float = 300.0,
    ) -> None:
        self.max_assembled = max_assembled
        self.stale_after = stale_after
        # id -> (buffer, received-seqs, last-activity monotonic time)
        self._partial: dict[bytes, tuple[bytearray, set, float]] = {}
        self._pending_bytes = 0

    def feed(self, data) -> Optional[Any]:
        if not protocol.is_chunk_frame(data):
            return data
        mv = memoryview(data)
        hdr_len = int.from_bytes(mv[4:8], "little")
        hdr = msgpack.unpackb(mv[8 : 8 + hdr_len], raw=False)
        chunk = mv[8 + hdr_len :]
        total, off, n, seq = hdr["z"], hdr["o"], hdr["n"], hdr["q"]
        stride = hdr.get("c", 0)
        if not (0 < total <= self.max_assembled):
            raise ValueError(
                f"chunk claims {total} assembled bytes (cap "
                f"{self.max_assembled}; BIOENGINE_RPC_MAX_ASSEMBLED_MB)"
            )
        if (
            stride < 1
            or not 0 <= seq < n
            or n != (total + stride - 1) // stride
            or off != seq * stride
            or len(chunk) != min(stride, total - off)
        ):
            raise ValueError(
                "inconsistent chunk header (offset/seq/count/stride)"
            )
        self._expire_stale()
        if hdr["id"] not in self._partial and (
            self._pending_bytes + total > self.max_assembled
        ):
            raise ValueError(
                "in-flight partial frames exceed the assembly budget "
                "(BIOENGINE_RPC_MAX_ASSEMBLED_MB)"
            )
        now = time.monotonic()
        if hdr["id"] not in self._partial:
            self._partial[hdr["id"]] = (bytearray(total), set(), now)
            self._pending_bytes += total
        buf, seen, _ = self._partial[hdr["id"]]
        if len(buf) != total:
            raise ValueError("chunk stream changed its claimed total")
        buf[off : off + len(chunk)] = chunk
        seen.add(seq)
        self._partial[hdr["id"]] = (buf, seen, now)
        if len(seen) < n:
            return None
        # every seq 0..n-1 present with validated stride offsets —
        # the buffer is fully covered, no zero-filled holes possible
        del self._partial[hdr["id"]]
        self._pending_bytes -= total
        return memoryview(buf).toreadonly()

    def _expire_stale(self) -> None:
        cutoff = time.monotonic() - self.stale_after
        for mid in [
            mid for mid, (_, _, ts) in self._partial.items() if ts < cutoff
        ]:
            buf, _, _ = self._partial.pop(mid)
            self._pending_bytes -= len(buf)

    @property
    def pending(self) -> int:
        return len(self._partial)


class ShmPinTracker:
    """Pins taken while decoding shm refs on the receive side.

    Each decoded array is a view over the store's mapping; the pin must
    outlive every such view or LRU eviction could recycle the bytes
    underneath it. Liveness is detected with ``weakref.finalize`` on
    the root ``np.frombuffer`` array: any numpy view derived from it
    keeps it alive through the ``.base`` chain (and ``memoryview(arr)``
    holds it via the exported Py_buffer), so the finalizer fires
    exactly when no consumer can reach the bytes anymore.
    (``memoryview.release()`` is NOT a usable signal — numpy exports
    from the underlying buffer owner, so release never raises.)

    The finalizer may run from GC in any thread mid-anything, so it
    only enqueues the key; ``drain`` — called from safe points after
    each dispatched message — performs the store release+delete (RPC
    payloads are one-shot: the receiver owns disposal)."""

    def __init__(self, store) -> None:
        self.store = store
        self._finalizers: dict[str, Any] = {}
        self._releasable: deque[str] = deque()

    def materialize(self, desc: dict) -> Any:
        """Descriptor {"k", "n", "d", "s"} / {"k", "n", "y"} -> value."""
        key, nbytes = desc["k"], desc["n"]
        view = self.store.get(key)
        if view is None:
            raise KeyError(
                f"shm object {key!r} missing — evicted before consume; "
                "size the store above the in-flight payload volume "
                "(docs/OPERATIONS.md)"
            )
        if desc.get("y"):
            data = bytes(view[:nbytes])  # bytes consumers get a copy
            view.release()
            self.store.release(key)
            self._try_delete(key)
            return data
        import numpy as np

        arr = np.frombuffer(view[:nbytes], dtype=np.dtype(desc["d"])).reshape(
            desc["s"]
        )
        self._finalizers[key] = weakref.finalize(
            arr, self._releasable.append, key
        )
        return arr

    def _try_delete(self, key: str) -> None:
        try:
            self.store.delete(key)
        except Exception:  # noqa: BLE001 — another peer may have raced
            pass

    def drain(self) -> int:
        """Release+delete objects whose consumers are gone; returns how
        many stay pinned."""
        while True:
            try:
                key = self._releasable.popleft()
            except IndexError:
                break
            self._finalizers.pop(key, None)
            self.store.release(key)
            self._try_delete(key)
        return len(self._finalizers)

    def close(self) -> None:
        # keys whose consumers are still alive KEEP their pins — a
        # closing connection must not let eviction recycle bytes under
        # live arrays; those pins persist until process exit
        self.drain()


# a scratch bytearray that ballooned past this is dropped instead of
# returned to the pool — one aborted pack of a 64 KB string must not
# pin that much capacity on the connection forever
_FAST_SCRATCH_RETAIN = 2 * protocol.FAST_THRESHOLD_DEFAULT + 65536


class Codec:
    """Per-connection encoder/decoder with negotiated capabilities."""

    def __init__(
        self,
        *,
        config: Optional[TransportConfig] = None,
        stats: Optional[RpcStats] = None,
    ):
        self.config = config or TransportConfig.from_env()
        self.stats = stats or RpcStats()
        self.oob = False                 # peer speaks PROTO_OOB1
        self.trace = False               # peer speaks PROTO_TRACE1
        self.fast = False                # peer speaks PROTO_FAST1
        self.shm_store = None            # negotiated same-host store
        self._tracker: Optional[ShmPinTracker] = None
        self._assembler = FrameAssembler(
            max_assembled=self.config.max_assembled
        )
        # reusable BEFS scratch buffers. A tiny pool (list.pop/append
        # are atomic under the GIL) instead of one shared bytearray:
        # encode can run concurrently on the event loop and in an
        # offload thread, and a scratch must never be shared mid-pack
        self._fast_pool: list[bytearray] = [bytearray()]
        # hoisted out of the per-frame wrappers: two attribute hops per
        # call add up at 4 codec invocations per round trip
        self._fast_threshold = self.config.fast_threshold

    # ---- negotiation --------------------------------------------------------

    def enable_shm(self, store) -> None:
        self.shm_store = store
        self._tracker = ShmPinTracker(store)

    # ---- encode -------------------------------------------------------------

    def _shm_put(self, buf: memoryview) -> Optional[str]:
        if self.shm_store is None or buf.nbytes < self.config.shm_threshold:
            return None
        key = f"rpc/{secrets.token_hex(12)}"
        try:
            ok = self.shm_store.try_put(key, buf)
        except Exception:  # noqa: BLE001 — store trouble must not kill the call
            ok = False
        if not ok:
            with self.stats.lock:
                self.stats.shm_fallbacks += 1
            return None
        with self.stats.lock:
            self.stats.shm_puts += 1
            self.stats.shm_put_bytes += buf.nbytes
        return key

    def encode_fast_frame(self, msg: dict) -> Optional[bytes]:
        """One BEFS frame for a fast-eligible message, else None (and
        the fallback counter ticks for the hot envelopes).

        Stats are updated WITHOUT the lock: fast frames are by
        construction small, so this path only ever runs on the event
        loop thread (the ``to_thread`` offload is for big payloads,
        which can never qualify). The counters are advisory — a lost
        increment against a concurrent locked full-path update is
        tolerable; a per-request lock acquire on the microsecond hot
        path is not (BE-PERF-301)."""
        t0 = _perf_counter()
        pool = self._fast_pool
        scratch = pool.pop() if pool else bytearray()
        frame = protocol.encode_fast(msg, self._fast_threshold, scratch)
        if len(scratch) <= _FAST_SCRATCH_RETAIN:
            pool.append(scratch)
        st = self.stats
        if frame is None:
            t = msg.get("t")
            if t == protocol.CALL or t == protocol.RESULT:
                st.fast_fallbacks += 1
            return None
        st.small_frames_out += 1
        st.encode_seconds += _perf_counter() - t0
        st.msgs_out += 1
        st.frames_out += 1
        st.bytes_out += len(frame)
        return frame

    def encode_fast_call_frame(
        self, call_id: str, service_id: str, method: str, args, kwargs: dict
    ) -> Optional[bytes]:
        """``encode_fast_frame`` from call-site arguments — the client
        request path never materializes the CALL dict when this hits
        (same unlocked-stats argument, BE-PERF-301)."""
        t0 = _perf_counter()
        pool = self._fast_pool
        scratch = pool.pop() if pool else bytearray()
        frame = protocol.encode_fast_call(
            call_id, service_id, method, args, kwargs,
            self._fast_threshold, scratch,
        )
        if len(scratch) <= _FAST_SCRATCH_RETAIN:
            pool.append(scratch)
        st = self.stats
        if frame is None:
            st.fast_fallbacks += 1
            return None
        st.small_frames_out += 1
        st.encode_seconds += _perf_counter() - t0
        st.msgs_out += 1
        st.frames_out += 1
        st.bytes_out += len(frame)
        return frame

    def encode_fast_result_frame(
        self, call_id: str, result: Any
    ) -> Optional[bytes]:
        """``encode_fast_frame`` from the handler's return value — the
        server inline-dispatch path never materializes the RESULT
        dict when this hits."""
        t0 = _perf_counter()
        pool = self._fast_pool
        scratch = pool.pop() if pool else bytearray()
        frame = protocol.encode_fast_result(
            call_id, result, self._fast_threshold, scratch
        )
        if len(scratch) <= _FAST_SCRATCH_RETAIN:
            pool.append(scratch)
        st = self.stats
        if frame is None:
            st.fast_fallbacks += 1
            return None
        st.small_frames_out += 1
        st.encode_seconds += _perf_counter() - t0
        st.msgs_out += 1
        st.frames_out += 1
        st.bytes_out += len(frame)
        return frame

    def encode_fast_stream_frame(
        self, call_id: str, seq: int, item: Any
    ) -> Optional[bytes]:
        """One BEFS stream-item frame — the per-token send path of a
        streaming call never materializes the STREAM dict when this
        hits (same unlocked-stats argument as the other fast encoders:
        a generation is hundreds of tiny frames)."""
        t0 = _perf_counter()
        pool = self._fast_pool
        scratch = pool.pop() if pool else bytearray()
        frame = protocol.encode_fast_stream(
            call_id, seq, item, self._fast_threshold, scratch
        )
        if len(scratch) <= _FAST_SCRATCH_RETAIN:
            pool.append(scratch)
        st = self.stats
        if frame is None:
            st.fast_fallbacks += 1
            return None
        st.small_frames_out += 1
        st.encode_seconds += _perf_counter() - t0
        st.msgs_out += 1
        st.frames_out += 1
        st.bytes_out += len(frame)
        return frame

    def decode_fast_stream_frame(self, data: bytes) -> Optional[tuple]:
        """``(call_id, seq, item)`` for a BEFS STREAM frame, else None
        — read loops feed the stream queue straight from the tuple."""
        t0 = _perf_counter()
        parsed = protocol.decode_fast_stream(data)
        if parsed is None:
            return None
        st = self.stats
        st.frames_in += 1
        st.bytes_in += len(data)
        st.small_frames_in += 1
        st.msgs_in += 1
        st.decode_seconds += _perf_counter() - t0
        return parsed

    def encode_frames(self, msg: dict) -> list:
        """Encode ``msg`` into the list of websocket messages to send."""
        if self.fast:
            frame = self.encode_fast_frame(msg)
            if frame is not None:
                return [frame]
        return self._encode_full(msg)

    def _encode_full(self, msg: dict) -> list:
        t0 = time.perf_counter()
        payload_info: dict = {}
        if not self.oob:
            frames = [protocol.encode(msg)]
        else:
            frame = protocol.encode_oob(
                msg, shm_put=self._shm_put, payload_info=payload_info
            )
            frames = chunk_frames(frame, self.config.frame_limit)
        with self.stats.lock:
            if not self.oob:
                self.stats.legacy_msgs_out += 1
            elif len(frames) > 1:
                self.stats.chunked_msgs_out += 1
            self.stats.oob_payloads_out += payload_info.get("n", 0)
            self.stats.oob_payload_bytes_out += payload_info.get("bytes", 0)
            self.stats.encode_seconds += time.perf_counter() - t0
            self.stats.msgs_out += 1
            self.stats.frames_out += len(frames)
            self.stats.bytes_out += sum(len(f) for f in frames)
        return frames

    async def encode_frames_async(self, msg: dict) -> list:
        """``encode_frames``, off-loop when the payload is large enough
        that serializing it inline would stall the event loop."""
        if self.fast:
            # the fast attempt is bounded (bails on the first oversize
            # or non-scalar value) so it runs inline and, when it hits,
            # skips the payload_nbytes walk entirely
            frame = self.encode_fast_frame(msg)
            if frame is not None:
                return [frame]
        if protocol.payload_nbytes(msg) >= self.config.offload_threshold:
            return await asyncio.to_thread(self._encode_full, msg)
        return self._encode_full(msg)

    # ---- decode -------------------------------------------------------------

    def _shm_materialize(self, desc: dict) -> Any:
        assert self._tracker is not None
        value = self._tracker.materialize(desc)
        with self.stats.lock:
            self.stats.shm_gets += 1
            self.stats.shm_get_bytes += desc.get("n", 0)
        return value

    def decode(self, data) -> Optional[dict]:
        """One received websocket message -> a complete message dict,
        or None while a chunked frame is still assembling."""
        t0 = time.perf_counter()
        whole = self._assembler.feed(data)
        if whole is None:
            with self.stats.lock:
                self.stats.frames_in += 1
                self.stats.bytes_in += len(data)
                self.stats.decode_seconds += time.perf_counter() - t0
            return None
        fast_in = False
        if protocol.is_fast_frame(whole):
            # dispatch by magic, not by the negotiated flag: only a
            # fast1 peer ever sends BEFS, but decode stays symmetric
            msg = protocol.decode_fast(whole)
            fast_in = True
        elif protocol.is_oob_frame(whole):
            msg = protocol.decode_oob(
                whole,
                shm_get=self._shm_materialize
                if self._tracker is not None
                else None,
            )
        else:
            msg = protocol.decode(whole)
        with self.stats.lock:
            self.stats.frames_in += 1
            self.stats.bytes_in += len(data)
            if whole is not data:
                self.stats.chunked_msgs_in += 1
            if fast_in:
                self.stats.small_frames_in += 1
            self.stats.msgs_in += 1
            self.stats.decode_seconds += time.perf_counter() - t0
        return msg

    def decode_fast_frame(self, data: bytes) -> dict:
        """Decode one BEFS frame (caller checked ``is_fast_frame``).
        BEFS frames are never chunked and never big enough to offload,
        so the read loops take this branch-free sync path — no
        assembler feed, no coroutine, and (same argument as
        ``encode_fast_frame``) no stats lock."""
        t0 = _perf_counter()
        msg = protocol.decode_fast(data)
        st = self.stats
        st.frames_in += 1
        st.bytes_in += len(data)
        st.small_frames_in += 1
        st.msgs_in += 1
        st.decode_seconds += _perf_counter() - t0
        return msg

    def decode_fast_call_frame(self, data: bytes) -> Optional[tuple]:
        """``(call_id, service_id, method, args, kwargs)`` for a BEFS
        CALL frame, else None — the server's inline dispatch runs off
        the tuple without building the envelope dict. A None return
        records no stats; the ``decode_fast_frame`` fallback does."""
        t0 = _perf_counter()
        parsed = protocol.decode_fast_call(data)
        if parsed is None:
            return None
        st = self.stats
        st.frames_in += 1
        st.bytes_in += len(data)
        st.small_frames_in += 1
        st.msgs_in += 1
        st.decode_seconds += _perf_counter() - t0
        return parsed

    def decode_fast_result_frame(self, data: bytes) -> Optional[tuple]:
        """``(call_id, value)`` for a BEFS RESULT frame, else None —
        the client read loop resolves the waiting future from the
        tuple without building the envelope dict. A None return
        records no stats; the ``decode_fast_frame`` fallback does."""
        t0 = _perf_counter()
        parsed = protocol.decode_fast_result(data)
        if parsed is None:
            return None
        st = self.stats
        st.frames_in += 1
        st.bytes_in += len(data)
        st.small_frames_in += 1
        st.msgs_in += 1
        st.decode_seconds += _perf_counter() - t0
        return parsed

    async def decode_async(self, data) -> Optional[dict]:
        if len(data) >= self.config.offload_threshold:
            return await asyncio.to_thread(self.decode, data)
        return self.decode(data)

    # ---- shm lifecycle ------------------------------------------------------

    def drain_pins(self) -> None:
        """Retry releasing store pins whose consumer views are gone —
        called after each dispatched message so one-shot RPC payloads
        leave the arena as soon as the handler drops them."""
        if self._tracker is not None:
            self._tracker.drain()

    def close(self) -> None:
        if self._tracker is not None:
            self._tracker.close()


def attach_store_by_name(name: str):
    """Best-effort attach to an existing named shm segment (the client
    side of negotiation). None when the native store is unavailable or
    the segment doesn't exist — the caller falls back to wire frames."""
    from bioengine_tpu.native import store as native_store

    if not native_store.native_available():
        return None
    try:
        return native_store.SharedObjectStore(name, create=False)
    except Exception:  # noqa: BLE001 — absent segment is a normal outcome
        return None
