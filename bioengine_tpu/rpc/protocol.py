"""Wire protocol for the BioEngine-TPU control plane.

The reference speaks hypha-rpc (an external WebSocket RPC service,
ref bioengine/worker/worker.py:522-612 connects out to it). This
framework ships its own control plane with the same *shape* —
service registration, method calls with injected caller context,
token auth — so deployments need no external RPC broker.

Two codecs share this module:

**Legacy** (``encode``/``decode``): one msgpack map; ndarrays ride as
ExtType(1) carrying ``dtype/shape/data`` packed *again* inside the
outer message. Every array crossing the plane is copied at least three
times per direction (``tobytes`` -> inner pack -> outer pack, then the
mirror on decode). Kept verbatim for interop with peers that predate
out-of-band framing.

**Out-of-band** (``encode_oob``/``decode_oob``): one scatter-gather
frame. A pre-walk extracts every large ndarray/bytes payload into a
buffer table and replaces it with a tiny ExtType(3) ref
(``{"i": idx, "d": dtype, "s": shape}``); the remaining small header
packs once, and raw buffers are appended 64-byte-aligned after it —
each payload is memcpy'd exactly once into the frame. ``decode_oob``
rebuilds arrays with ``np.frombuffer`` directly over the received
frame's memoryview: **zero** payload copies on receive. ExtType(4)
refs point into the host-shared shm object store instead (key, not
bytes): the receive side maps those zero-copy too, so a same-host hop
costs one copy total (the store put). Transport-level concerns —
chunked multi-frame sends, shm negotiation, stats — live in
``rpc/transport.py``.

Frame layout (all integers little-endian)::

    b"BEF1" | u32 meta_len | meta | pad to 64 | buf0 | pad | buf1 | ...
    meta = msgpack {"h": <packed message with ExtType refs>,
                    "b": [[rel_offset, length], ...]}

``rel_offset`` is relative to ``payload_start =
align64(8 + meta_len)`` so every buffer lands 64-byte-aligned in the
assembled frame (aligned ``np.frombuffer`` views are vectorization-
friendly). The magic byte 0x42 can never open a legacy message (a
msgpack map starts 0x80-0x8f or 0xde/0xdf), so ``is_oob_frame``
dispatch is unambiguous.
"""

from __future__ import annotations

import struct
import traceback
from typing import Any, Callable, Optional

import msgpack
import numpy as np

# message types
REGISTER = "register"          # client -> server: register a service
UNREGISTER = "unregister"
CALL = "call"                  # caller -> server -> provider
RESULT = "result"              # provider -> server -> caller
ERROR = "error"
TOKEN = "token"                # generate_token request
LIST = "list_services"
PING = "ping"
PONG = "pong"
SHM_ACK = "shm_ack"            # client proves it mapped the shared store
STREAM = "stream"              # provider -> server -> caller: one item of
                               # a streaming call (ordered by seq; the
                               # closing RESULT carries the final count)

# wire identifiers
OOB_MAGIC = b"BEF1"            # out-of-band scatter-gather frame
CHUNK_MAGIC = b"BEC1"          # one chunk of an oversized frame
FAST_MAGIC = b"BEFS"           # fixed-layout small-request fast frame
PROTO_OOB1 = "oob1"            # negotiated capability name
PROTO_FAST1 = "fast1"          # small-request fast frames (BEFS)
PROTO_TRACE1 = "trace1"        # request-trace fields on CALL/RESULT
PROTO_TELEM1 = "telem1"        # push-telemetry verbs on the serve-router
PROTO_MESH1 = "mesh1"          # cross-host mesh shards (mesh_shard on
                               # start_replica, stage activations over OOB)
PROTO_EPOCH1 = "epoch1"        # controller-epoch fencing: epoch kwarg on
                               # placement/lifecycle verbs, rejected typed
                               # when stale (StaleEpochError)
PROTO_STREAM1 = "stream1"      # streaming calls: async-generator service
                               # methods emit per-item STREAM frames
                               # (fast-frame kind 3 when eligible) closed
                               # by a counting RESULT

EXT_NDARRAY = 1                # legacy inline array (double-packed)
EXT_EXCEPTION = 2
EXT_OOB_REF = 3                # ref into this frame's buffer table
EXT_SHM_REF = 4                # ref into the host-shared object store

# payloads below this stay inline as legacy ExtType(1) — the envelope
# overhead of a table entry isn't worth it for scalars and tiny arrays
INLINE_LIMIT = 1024


def _pack_exception(obj: Exception) -> msgpack.ExtType:
    return msgpack.ExtType(
        EXT_EXCEPTION,
        msgpack.packb(
            {
                "type": type(obj).__name__,
                "message": str(obj),
                "traceback": "".join(
                    traceback.format_exception(obj)
                )[-4000:],
            }
        ),
    )


def _pack_inline_ndarray(obj: np.ndarray) -> msgpack.ExtType:
    return msgpack.ExtType(
        EXT_NDARRAY,
        msgpack.packb(
            {
                "dtype": obj.dtype.str,
                "shape": list(obj.shape),
                "data": obj.tobytes(),
            }
        ),
    )


def _default(obj: Any) -> Any:
    if isinstance(obj, np.ndarray):
        return _pack_inline_ndarray(obj)
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, Exception):
        return _pack_exception(obj)
    raise TypeError(f"Cannot serialize {type(obj)}")


class RemoteError(RuntimeError):
    """An exception raised on the provider side of an RPC call."""

    def __init__(self, type_name: str, message: str, remote_traceback: str = ""):
        super().__init__(f"{type_name}: {message}")
        self.type_name = type_name
        self.remote_traceback = remote_traceback


def _ext_hook(code: int, data: bytes) -> Any:
    if code == EXT_NDARRAY:
        d = msgpack.unpackb(data)
        return np.frombuffer(d["data"], dtype=np.dtype(d["dtype"])).reshape(
            d["shape"]
        )
    if code == EXT_EXCEPTION:
        d = msgpack.unpackb(data)
        return RemoteError(d["type"], d["message"], d.get("traceback", ""))
    return msgpack.ExtType(code, data)


def encode(msg: dict) -> bytes:
    """Legacy single-blob encoding (interop baseline)."""
    return msgpack.packb(msg, default=_default, use_bin_type=True)


def decode(data) -> dict:
    """Legacy single-blob decoding. Shm refs cannot appear here (they
    require a negotiated store); an ExtType(4) raises loudly rather
    than returning a silent placeholder."""
    return msgpack.unpackb(bytes(data), ext_hook=_ext_hook, raw=False)


# ---------------------------------------------------------------------------
# Out-of-band codec
# ---------------------------------------------------------------------------


def is_oob_frame(data) -> bool:
    return bytes(data[:4]) == OOB_MAGIC


def is_chunk_frame(data) -> bool:
    return bytes(data[:4]) == CHUNK_MAGIC


def _align64(n: int) -> int:
    return (n + 63) & ~63


def payload_nbytes(obj: Any, _depth: int = 0) -> int:
    """Recursive estimate of the raw tensor/bytes payload a message
    carries — what decides off-loop encode and chunking, computed
    without serializing anything."""
    if _depth > 8:
        return 0
    if isinstance(obj, np.ndarray):
        return obj.nbytes
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    if isinstance(obj, dict):
        return sum(payload_nbytes(v, _depth + 1) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(payload_nbytes(v, _depth + 1) for v in obj)
    return 0


def _extract(obj: Any, buffers: list, shm_put: Optional[Callable]) -> Any:
    """Pre-walk replacing large payloads with ExtType refs.

    ``buffers`` collects flat C-order memoryviews (the scatter list);
    ``shm_put(buf) -> key | None`` diverts a buffer into the shared
    store instead (None = store full/absent, fall back to the wire)."""
    if isinstance(obj, np.ndarray):
        if obj.nbytes < INLINE_LIMIT:
            return _pack_inline_ndarray(obj)
        arr = np.ascontiguousarray(obj)  # copies only if non-contiguous
        desc = {"d": arr.dtype.str, "s": list(arr.shape)}
        return _ref_for(memoryview(arr).cast("B"), desc, buffers, shm_put)
    if isinstance(obj, (bytes, bytearray, memoryview)):
        buf = memoryview(obj).cast("B") if not isinstance(obj, bytes) else obj
        if len(buf) < INLINE_LIMIT:
            return bytes(buf) if not isinstance(obj, bytes) else obj
        return _ref_for(
            buf if isinstance(buf, memoryview) else memoryview(buf),
            {"y": 1},
            buffers,
            shm_put,
        )
    if isinstance(obj, dict):
        return {k: _extract(v, buffers, shm_put) for k, v in obj.items()}
    if isinstance(obj, msgpack.ExtType):
        # ExtType is a namedtuple — the tuple branch below would
        # flatten it into [code, data]; pass it through to msgpack
        return obj
    if isinstance(obj, (list, tuple)):
        return [_extract(v, buffers, shm_put) for v in obj]
    return obj


def _ref_for(
    buf: memoryview, desc: dict, buffers: list, shm_put: Optional[Callable]
) -> msgpack.ExtType:
    if shm_put is not None:
        key = shm_put(buf)
        if key is not None:
            return msgpack.ExtType(
                EXT_SHM_REF,
                msgpack.packb({**desc, "k": key, "n": buf.nbytes}),
            )
    idx = len(buffers)
    buffers.append(buf)
    return msgpack.ExtType(EXT_OOB_REF, msgpack.packb({**desc, "i": idx}))


def encode_oob(
    msg: dict,
    shm_put: Optional[Callable] = None,
    payload_info: Optional[dict] = None,
) -> bytearray:
    """Encode ``msg`` as one scatter-gather frame.

    Each extracted payload buffer is written into the frame exactly
    once (or diverted to the shared store via ``shm_put``); everything
    else packs into the small header. Returns the assembled frame —
    ``bytearray`` so callers can send slices without another copy.
    ``payload_info`` (when given) receives ``{"n", "bytes"}`` of the
    wire-extracted buffers — the codec's RpcStats feed."""
    buffers: list[memoryview] = []
    header = msgpack.packb(
        _extract(msg, buffers, shm_put), default=_default, use_bin_type=True
    )
    if payload_info is not None:
        payload_info["n"] = len(buffers)
        payload_info["bytes"] = sum(b.nbytes for b in buffers)
    table = []
    rel = 0
    for buf in buffers:
        rel = _align64(rel)
        table.append([rel, buf.nbytes])
        rel += buf.nbytes
    meta = msgpack.packb({"h": header, "b": table})
    payload_start = _align64(8 + len(meta))
    frame = bytearray(payload_start + rel)
    frame[0:4] = OOB_MAGIC
    frame[4:8] = len(meta).to_bytes(4, "little")
    frame[8 : 8 + len(meta)] = meta
    for (off, length), buf in zip(table, buffers):
        frame[payload_start + off : payload_start + off + length] = buf
    return frame


def decode_oob(data, shm_get: Optional[Callable] = None) -> dict:
    """Decode a scatter-gather frame.

    Arrays referenced through the buffer table come back as
    ``np.frombuffer`` views **over the received frame** — zero copies,
    read-only (mutate via ``.copy()`` when needed, same contract the
    legacy decoder already had). ``shm_get(descriptor) -> value``
    materializes store-resident payloads (array view over the shm
    segment, or bytes) and owns their pin lifetime
    (rpc.transport.ShmPinTracker)."""
    mv = memoryview(data)
    if bytes(mv[:4]) != OOB_MAGIC:
        raise ValueError("not an out-of-band frame")
    meta_len = int.from_bytes(mv[4:8], "little")
    meta = msgpack.unpackb(mv[8 : 8 + meta_len], raw=False)
    table = meta["b"]
    payload = mv[_align64(8 + meta_len) :]

    def hook(code: int, ext_data: bytes) -> Any:
        if code == EXT_OOB_REF:
            d = msgpack.unpackb(ext_data)
            off, length = table[d["i"]]
            raw = payload[off : off + length]
            if d.get("y"):
                return bytes(raw)
            return np.frombuffer(raw, dtype=np.dtype(d["d"])).reshape(d["s"])
        if code == EXT_SHM_REF:
            d = msgpack.unpackb(ext_data)
            if shm_get is None:
                raise RuntimeError(
                    "message references the shared object store but this "
                    "peer has none attached (negotiation bug)"
                )
            # shm_get materializes the value itself (array view over
            # the segment, or bytes) because pin lifetime must be tied
            # to the object it hands out — see transport.ShmPinTracker
            return shm_get(d)
        return _ext_hook(code, ext_data)

    return msgpack.unpackb(meta["h"], ext_hook=hook, raw=False)


# ---------------------------------------------------------------------------
# Small-request fast frames (BEFS)
# ---------------------------------------------------------------------------
#
# The microsecond budget of a 1 KB call is dominated by envelope work:
# the oob pre-walk, a double msgpack pack, and ExtType dispatch. A fast
# frame is a fixed-layout struct-packed encoding for the two hot
# envelopes only — an untraced CALL and a span-free RESULT — whose
# values are scalars/strings/small bytes (shallow lists/dicts of the
# same allowed, so batched ``replica_call`` envelopes qualify). One
# single-pass pack into a caller-supplied scratch buffer, no msgpack,
# no pre-walk. Anything else — traces, spans, ndarrays, exceptions,
# oversize values — makes ``encode_fast`` return None and the caller
# falls back to the full codec, so the fast path can never change what
# a message can carry. Negotiated as ``fast1``; like the oob magic,
# 0x42 cannot open a legacy msgpack map, so dispatch stays unambiguous.
#
# Frame layout (little-endian)::
#
#     b"BEFS" | u8 kind | body
#     kind 1 (CALL):   str16 call_id | str16 service_id | str16 method
#                      | u8 n_args | value*  | u8 n_kwargs
#                      | (str16 key, value)*
#     kind 2 (RESULT): str16 call_id | value
#     str16 = u16 len | utf-8 bytes
#     value = u8 tag | payload    (tags below)

FAST_KIND_CALL = 1
FAST_KIND_RESULT = 2
FAST_KIND_STREAM = 3           # str16 call_id | u32 seq | value item

_FT_NONE = 0
_FT_TRUE = 1
_FT_FALSE = 2
_FT_INT = 3       # s64
_FT_FLOAT = 4     # f64
_FT_STR = 5       # u32 len | utf-8
_FT_BYTES = 6     # u32 len | raw
_FT_LIST = 7      # u8 count | value*
_FT_DICT = 8      # u8 count | (str16 key, value)*

# Per-value size guard: a single str/bytes longer than this can never
# fit a fast frame regardless of the negotiated limit, so bail before
# copying it into the scratch buffer.
_FAST_VALUE_LIMIT = 65536
# Default whole-frame threshold; transport exposes it as a config knob
# (BIOENGINE_RPC_FAST_THRESHOLD).
FAST_THRESHOLD_DEFAULT = 4096

_PACK_Q = struct.Struct("<q").pack
_PACK_D = struct.Struct("<d").pack
_UNPACK_Q = struct.Struct("<q").unpack_from
_UNPACK_D = struct.Struct("<d").unpack_from
_UNPACK_H = struct.Struct("<H").unpack_from
_UNPACK_I = struct.Struct("<I").unpack_from

_FAST_CALL_PREFIX = FAST_MAGIC + bytes([FAST_KIND_CALL])
_FAST_RESULT_PREFIX = FAST_MAGIC + bytes([FAST_KIND_RESULT])
_FAST_STREAM_PREFIX = FAST_MAGIC + bytes([FAST_KIND_STREAM])


class _FastUnsupported(Exception):
    """Internal: value not expressible in a fast frame (fall back)."""


def is_fast_frame(data) -> bool:
    return bytes(data[:4]) == FAST_MAGIC


def _fast_str16(out: bytearray, s: str) -> None:
    b = s.encode("utf-8")
    if len(b) > 65535:
        raise _FastUnsupported
    out += len(b).to_bytes(2, "little")
    out += b


def _fast_pack_value(out: bytearray, v: Any, depth: int) -> None:
    t = type(v)
    if v is None:
        out.append(_FT_NONE)
    elif t is bool:
        out.append(_FT_TRUE if v else _FT_FALSE)
    elif t is int:
        out.append(_FT_INT)
        out += _PACK_Q(v)  # struct.error on >64-bit -> fallback
    elif t is float:
        out.append(_FT_FLOAT)
        out += _PACK_D(v)
    elif t is str:
        b = v.encode("utf-8")
        if len(b) > _FAST_VALUE_LIMIT:
            raise _FastUnsupported
        out.append(_FT_STR)
        out += len(b).to_bytes(4, "little")
        out += b
    elif t is bytes:
        if len(v) > _FAST_VALUE_LIMIT:
            raise _FastUnsupported
        out.append(_FT_BYTES)
        out += len(v).to_bytes(4, "little")
        out += v
    elif t is list or t is tuple:
        if depth >= 6 or len(v) > 255:
            raise _FastUnsupported
        out.append(_FT_LIST)
        out.append(len(v))
        for item in v:
            _fast_pack_value(out, item, depth + 1)
    elif t is dict:
        if depth >= 6 or len(v) > 255:
            raise _FastUnsupported
        out.append(_FT_DICT)
        out.append(len(v))
        for k, item in v.items():
            if type(k) is not str:
                raise _FastUnsupported
            _fast_str16(out, k)
            _fast_pack_value(out, item, depth + 1)
    else:
        # exact-type dispatch on purpose: np scalars, Exceptions,
        # ndarrays, ExtType, user subclasses all land here -> full codec
        raise _FastUnsupported


def encode_fast(
    msg: dict,
    limit: int = FAST_THRESHOLD_DEFAULT,
    scratch: Optional[bytearray] = None,
) -> Optional[bytes]:
    """Encode ``msg`` as one BEFS frame, or return None when it is not
    fast-eligible (caller falls back to the full codec).

    Only the two hot envelopes qualify — a CALL without a trace
    attachment and a RESULT without piggybacked spans — and only when
    every value packs into the tag scheme above and the whole frame
    stays within ``limit`` bytes. ``scratch`` is a reusable per-
    connection buffer; the returned value is an immutable copy so the
    scratch can be reused immediately (websocket sends may be queued).
    """
    try:
        t = msg.get("t")
        if t == CALL:
            if len(msg) != 6:
                return None
            return encode_fast_call(
                msg["call_id"],
                msg["service_id"],
                msg["method"],
                msg["args"],
                msg["kwargs"],
                limit,
                scratch,
            )
        if t == RESULT:
            if len(msg) != 3:
                return None
            return encode_fast_result(
                msg["call_id"], msg["result"], limit, scratch
            )
        return None
    except KeyError:
        return None


def encode_fast_call(
    call_id: str,
    service_id: str,
    method: str,
    args,
    kwargs: dict,
    limit: int = FAST_THRESHOLD_DEFAULT,
    scratch: Optional[bytearray] = None,
) -> Optional[bytes]:
    """``encode_fast`` for a CALL, taken directly from the call-site
    arguments — the request hot path skips building (and immediately
    re-walking) the envelope dict entirely. Byte-identical to encoding
    the equivalent dict through ``encode_fast``."""
    try:
        if (
            type(call_id) is not str
            or type(service_id) is not str
            or type(method) is not str
            or (type(args) is not list and type(args) is not tuple)
            or type(kwargs) is not dict
            or len(args) > 255
            or len(kwargs) > 255
        ):
            return None
        out = scratch if scratch is not None else bytearray()
        del out[:]
        out += _FAST_CALL_PREFIX
        _fast_str16(out, call_id)
        _fast_str16(out, service_id)
        _fast_str16(out, method)
        out.append(len(args))
        for v in args:
            _fast_pack_value(out, v, 0)
        out.append(len(kwargs))
        for k, v in kwargs.items():
            if type(k) is not str:
                return None
            _fast_str16(out, k)
            _fast_pack_value(out, v, 0)
        if len(out) > limit:
            return None
        return bytes(out)
    except (_FastUnsupported, struct.error, OverflowError):
        return None


def encode_fast_result(
    call_id: str,
    result: Any,
    limit: int = FAST_THRESHOLD_DEFAULT,
    scratch: Optional[bytearray] = None,
) -> Optional[bytes]:
    """``encode_fast`` for a RESULT, taken directly from the handler's
    return value — same direct-argument shortcut as
    ``encode_fast_call``."""
    try:
        if type(call_id) is not str:
            return None
        out = scratch if scratch is not None else bytearray()
        del out[:]
        out += _FAST_RESULT_PREFIX
        _fast_str16(out, call_id)
        _fast_pack_value(out, result, 0)
        if len(out) > limit:
            return None
        return bytes(out)
    except (_FastUnsupported, struct.error, OverflowError):
        return None


def encode_fast_stream(
    call_id: str,
    seq: int,
    item: Any,
    limit: int = FAST_THRESHOLD_DEFAULT,
    scratch: Optional[bytearray] = None,
) -> Optional[bytes]:
    """One stream item as a BEFS frame. Per-token sends are the entire
    point of the stream plane — a generation emits hundreds of tiny
    frames per request, so each rides the same single-pass fixed-layout
    encoding as a fast RESULT. None when the item isn't fast-eligible
    (caller falls back to the full-codec STREAM envelope)."""
    try:
        if type(call_id) is not str or seq < 0 or seq > 0xFFFFFFFF:
            return None
        out = scratch if scratch is not None else bytearray()
        del out[:]
        out += _FAST_STREAM_PREFIX
        _fast_str16(out, call_id)
        out += seq.to_bytes(4, "little")
        _fast_pack_value(out, item, 0)
        if len(out) > limit:
            return None
        return bytes(out)
    except (_FastUnsupported, struct.error, OverflowError):
        return None


def decode_fast_stream(data) -> Optional[tuple]:
    """``(call_id, seq, item)`` for a BEFS STREAM frame, None for any
    other kind — mirrors ``decode_fast_result``."""
    buf = bytes(data)
    if buf[4] != FAST_KIND_STREAM:  # caller already checked the magic
        return None
    call_id, pos = _fast_read_str16(buf, 5)
    seq = _UNPACK_I(buf, pos)[0]
    item, _ = _fast_read_value(buf, pos + 4)
    return call_id, seq, item


def _fast_read_str16(buf: bytes, pos: int):
    n = _UNPACK_H(buf, pos)[0]  # no slice allocation on the hot path
    pos += 2
    end = pos + n
    return str(buf[pos:end], "utf-8"), end


def _fast_read_value(buf: bytes, pos: int):
    tag = buf[pos]
    pos += 1
    if tag == _FT_NONE:
        return None, pos
    if tag == _FT_TRUE:
        return True, pos
    if tag == _FT_FALSE:
        return False, pos
    if tag == _FT_INT:
        return _UNPACK_Q(buf, pos)[0], pos + 8
    if tag == _FT_FLOAT:
        return _UNPACK_D(buf, pos)[0], pos + 8
    if tag == _FT_STR:
        n = _UNPACK_I(buf, pos)[0]
        pos += 4
        end = pos + n
        return str(buf[pos:end], "utf-8"), end
    if tag == _FT_BYTES:
        n = _UNPACK_I(buf, pos)[0]
        pos += 4
        end = pos + n
        return buf[pos:end], end
    if tag == _FT_LIST:
        n = buf[pos]
        pos += 1
        out = []
        for _ in range(n):
            v, pos = _fast_read_value(buf, pos)
            out.append(v)
        return out, pos
    if tag == _FT_DICT:
        n = buf[pos]
        pos += 1
        d = {}
        for _ in range(n):
            k, pos = _fast_read_str16(buf, pos)
            v, pos = _fast_read_value(buf, pos)
            d[k] = v
        return d, pos
    raise ValueError(f"bad fast-frame value tag {tag}")


def decode_fast(data) -> dict:
    """Decode a BEFS frame back into the canonical message dict —
    identical in shape and value to what ``decode`` would return for
    the same message through the legacy codec (tuples become lists in
    both, matching msgpack)."""
    buf = bytes(data)
    if buf[:4] != FAST_MAGIC:
        raise ValueError("not a fast frame")
    kind = buf[4]
    pos = 5
    if kind == FAST_KIND_CALL:
        call_id, service_id, method, args, kwargs = decode_fast_call(buf)
        return {
            "t": CALL,
            "call_id": call_id,
            "service_id": service_id,
            "method": method,
            "args": args,
            "kwargs": kwargs,
        }
    if kind == FAST_KIND_RESULT:
        call_id, pos = _fast_read_str16(buf, pos)
        v, pos = _fast_read_value(buf, pos)
        return {"t": RESULT, "call_id": call_id, "result": v}
    if kind == FAST_KIND_STREAM:
        call_id, pos = _fast_read_str16(buf, pos)
        seq = _UNPACK_I(buf, pos)[0]
        v, _ = _fast_read_value(buf, pos + 4)
        return {"t": STREAM, "call_id": call_id, "seq": seq, "item": v}
    raise ValueError(f"bad fast-frame kind {kind}")


def decode_fast_call(data) -> Optional[tuple]:
    """``(call_id, service_id, method, args, kwargs)`` for a BEFS CALL
    frame, None for any other kind — the server's inline dispatch runs
    the handler straight off the tuple without materializing the
    envelope dict."""
    buf = bytes(data)
    if buf[4] != FAST_KIND_CALL:  # caller already checked the magic
        return None
    call_id, pos = _fast_read_str16(buf, 5)
    service_id, pos = _fast_read_str16(buf, pos)
    method, pos = _fast_read_str16(buf, pos)
    n = buf[pos]
    pos += 1
    args = []
    for _ in range(n):
        v, pos = _fast_read_value(buf, pos)
        args.append(v)
    n = buf[pos]
    pos += 1
    kwargs = {}
    for _ in range(n):
        k, pos = _fast_read_str16(buf, pos)
        v, pos = _fast_read_value(buf, pos)
        kwargs[k] = v
    return call_id, service_id, method, args, kwargs


def decode_fast_result(data) -> Optional[tuple]:
    """``(call_id, value)`` for a BEFS RESULT frame, None for any
    other kind (the caller falls back to ``decode_fast``). The waiting
    future gets the value directly — no envelope dict is materialized
    on the response hot path."""
    buf = bytes(data)
    if buf[4] != FAST_KIND_RESULT:  # caller already checked the magic
        return None
    call_id, pos = _fast_read_str16(buf, 5)
    v, _ = _fast_read_value(buf, pos)
    return call_id, v
