"""Wire protocol for the BioEngine-TPU control plane.

The reference speaks hypha-rpc (an external WebSocket RPC service,
ref bioengine/worker/worker.py:522-612 connects out to it). This
framework ships its own control plane with the same *shape* —
service registration, method calls with injected caller context,
token auth — so deployments need no external RPC broker.

Messages are msgpack maps with a ``t`` (type) field. Payloads pass
through ``encode``/``decode`` which handle numpy arrays (zero-copy
raw-bytes + dtype/shape envelope), bytes, and Exception values.
"""

from __future__ import annotations

import traceback
from typing import Any

import msgpack
import numpy as np

# message types
REGISTER = "register"          # client -> server: register a service
UNREGISTER = "unregister"
CALL = "call"                  # caller -> server -> provider
RESULT = "result"              # provider -> server -> caller
ERROR = "error"
TOKEN = "token"                # generate_token request
LIST = "list_services"
PING = "ping"
PONG = "pong"


def _default(obj: Any) -> Any:
    if isinstance(obj, np.ndarray):
        return msgpack.ExtType(
            1,
            msgpack.packb(
                {
                    "dtype": obj.dtype.str,
                    "shape": list(obj.shape),
                    "data": obj.tobytes(),
                }
            ),
        )
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, Exception):
        return msgpack.ExtType(
            2,
            msgpack.packb(
                {
                    "type": type(obj).__name__,
                    "message": str(obj),
                    "traceback": "".join(
                        traceback.format_exception(obj)
                    )[-4000:],
                }
            ),
        )
    raise TypeError(f"Cannot serialize {type(obj)}")


class RemoteError(RuntimeError):
    """An exception raised on the provider side of an RPC call."""

    def __init__(self, type_name: str, message: str, remote_traceback: str = ""):
        super().__init__(f"{type_name}: {message}")
        self.type_name = type_name
        self.remote_traceback = remote_traceback


def _ext_hook(code: int, data: bytes) -> Any:
    if code == 1:
        d = msgpack.unpackb(data)
        return np.frombuffer(d["data"], dtype=np.dtype(d["dtype"])).reshape(
            d["shape"]
        )
    if code == 2:
        d = msgpack.unpackb(data)
        return RemoteError(d["type"], d["message"], d.get("traceback", ""))
    return msgpack.ExtType(code, data)


def encode(msg: dict) -> bytes:
    return msgpack.packb(msg, default=_default, use_bin_type=True)


def decode(data: bytes) -> dict:
    return msgpack.unpackb(data, ext_hook=_ext_hook, raw=False)
