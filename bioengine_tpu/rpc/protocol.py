"""Wire protocol for the BioEngine-TPU control plane.

The reference speaks hypha-rpc (an external WebSocket RPC service,
ref bioengine/worker/worker.py:522-612 connects out to it). This
framework ships its own control plane with the same *shape* —
service registration, method calls with injected caller context,
token auth — so deployments need no external RPC broker.

Two codecs share this module:

**Legacy** (``encode``/``decode``): one msgpack map; ndarrays ride as
ExtType(1) carrying ``dtype/shape/data`` packed *again* inside the
outer message. Every array crossing the plane is copied at least three
times per direction (``tobytes`` -> inner pack -> outer pack, then the
mirror on decode). Kept verbatim for interop with peers that predate
out-of-band framing.

**Out-of-band** (``encode_oob``/``decode_oob``): one scatter-gather
frame. A pre-walk extracts every large ndarray/bytes payload into a
buffer table and replaces it with a tiny ExtType(3) ref
(``{"i": idx, "d": dtype, "s": shape}``); the remaining small header
packs once, and raw buffers are appended 64-byte-aligned after it —
each payload is memcpy'd exactly once into the frame. ``decode_oob``
rebuilds arrays with ``np.frombuffer`` directly over the received
frame's memoryview: **zero** payload copies on receive. ExtType(4)
refs point into the host-shared shm object store instead (key, not
bytes): the receive side maps those zero-copy too, so a same-host hop
costs one copy total (the store put). Transport-level concerns —
chunked multi-frame sends, shm negotiation, stats — live in
``rpc/transport.py``.

Frame layout (all integers little-endian)::

    b"BEF1" | u32 meta_len | meta | pad to 64 | buf0 | pad | buf1 | ...
    meta = msgpack {"h": <packed message with ExtType refs>,
                    "b": [[rel_offset, length], ...]}

``rel_offset`` is relative to ``payload_start =
align64(8 + meta_len)`` so every buffer lands 64-byte-aligned in the
assembled frame (aligned ``np.frombuffer`` views are vectorization-
friendly). The magic byte 0x42 can never open a legacy message (a
msgpack map starts 0x80-0x8f or 0xde/0xdf), so ``is_oob_frame``
dispatch is unambiguous.
"""

from __future__ import annotations

import traceback
from typing import Any, Callable, Optional

import msgpack
import numpy as np

# message types
REGISTER = "register"          # client -> server: register a service
UNREGISTER = "unregister"
CALL = "call"                  # caller -> server -> provider
RESULT = "result"              # provider -> server -> caller
ERROR = "error"
TOKEN = "token"                # generate_token request
LIST = "list_services"
PING = "ping"
PONG = "pong"
SHM_ACK = "shm_ack"            # client proves it mapped the shared store

# wire identifiers
OOB_MAGIC = b"BEF1"            # out-of-band scatter-gather frame
CHUNK_MAGIC = b"BEC1"          # one chunk of an oversized frame
PROTO_OOB1 = "oob1"            # negotiated capability name
PROTO_TRACE1 = "trace1"        # request-trace fields on CALL/RESULT
PROTO_TELEM1 = "telem1"        # push-telemetry verbs on the serve-router
PROTO_MESH1 = "mesh1"          # cross-host mesh shards (mesh_shard on
                               # start_replica, stage activations over OOB)
PROTO_EPOCH1 = "epoch1"        # controller-epoch fencing: epoch kwarg on
                               # placement/lifecycle verbs, rejected typed
                               # when stale (StaleEpochError)

EXT_NDARRAY = 1                # legacy inline array (double-packed)
EXT_EXCEPTION = 2
EXT_OOB_REF = 3                # ref into this frame's buffer table
EXT_SHM_REF = 4                # ref into the host-shared object store

# payloads below this stay inline as legacy ExtType(1) — the envelope
# overhead of a table entry isn't worth it for scalars and tiny arrays
INLINE_LIMIT = 1024


def _pack_exception(obj: Exception) -> msgpack.ExtType:
    return msgpack.ExtType(
        EXT_EXCEPTION,
        msgpack.packb(
            {
                "type": type(obj).__name__,
                "message": str(obj),
                "traceback": "".join(
                    traceback.format_exception(obj)
                )[-4000:],
            }
        ),
    )


def _pack_inline_ndarray(obj: np.ndarray) -> msgpack.ExtType:
    return msgpack.ExtType(
        EXT_NDARRAY,
        msgpack.packb(
            {
                "dtype": obj.dtype.str,
                "shape": list(obj.shape),
                "data": obj.tobytes(),
            }
        ),
    )


def _default(obj: Any) -> Any:
    if isinstance(obj, np.ndarray):
        return _pack_inline_ndarray(obj)
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, Exception):
        return _pack_exception(obj)
    raise TypeError(f"Cannot serialize {type(obj)}")


class RemoteError(RuntimeError):
    """An exception raised on the provider side of an RPC call."""

    def __init__(self, type_name: str, message: str, remote_traceback: str = ""):
        super().__init__(f"{type_name}: {message}")
        self.type_name = type_name
        self.remote_traceback = remote_traceback


def _ext_hook(code: int, data: bytes) -> Any:
    if code == EXT_NDARRAY:
        d = msgpack.unpackb(data)
        return np.frombuffer(d["data"], dtype=np.dtype(d["dtype"])).reshape(
            d["shape"]
        )
    if code == EXT_EXCEPTION:
        d = msgpack.unpackb(data)
        return RemoteError(d["type"], d["message"], d.get("traceback", ""))
    return msgpack.ExtType(code, data)


def encode(msg: dict) -> bytes:
    """Legacy single-blob encoding (interop baseline)."""
    return msgpack.packb(msg, default=_default, use_bin_type=True)


def decode(data) -> dict:
    """Legacy single-blob decoding. Shm refs cannot appear here (they
    require a negotiated store); an ExtType(4) raises loudly rather
    than returning a silent placeholder."""
    return msgpack.unpackb(bytes(data), ext_hook=_ext_hook, raw=False)


# ---------------------------------------------------------------------------
# Out-of-band codec
# ---------------------------------------------------------------------------


def is_oob_frame(data) -> bool:
    return bytes(data[:4]) == OOB_MAGIC


def is_chunk_frame(data) -> bool:
    return bytes(data[:4]) == CHUNK_MAGIC


def _align64(n: int) -> int:
    return (n + 63) & ~63


def payload_nbytes(obj: Any, _depth: int = 0) -> int:
    """Recursive estimate of the raw tensor/bytes payload a message
    carries — what decides off-loop encode and chunking, computed
    without serializing anything."""
    if _depth > 8:
        return 0
    if isinstance(obj, np.ndarray):
        return obj.nbytes
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    if isinstance(obj, dict):
        return sum(payload_nbytes(v, _depth + 1) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(payload_nbytes(v, _depth + 1) for v in obj)
    return 0


def _extract(obj: Any, buffers: list, shm_put: Optional[Callable]) -> Any:
    """Pre-walk replacing large payloads with ExtType refs.

    ``buffers`` collects flat C-order memoryviews (the scatter list);
    ``shm_put(buf) -> key | None`` diverts a buffer into the shared
    store instead (None = store full/absent, fall back to the wire)."""
    if isinstance(obj, np.ndarray):
        if obj.nbytes < INLINE_LIMIT:
            return _pack_inline_ndarray(obj)
        arr = np.ascontiguousarray(obj)  # copies only if non-contiguous
        desc = {"d": arr.dtype.str, "s": list(arr.shape)}
        return _ref_for(memoryview(arr).cast("B"), desc, buffers, shm_put)
    if isinstance(obj, (bytes, bytearray, memoryview)):
        buf = memoryview(obj).cast("B") if not isinstance(obj, bytes) else obj
        if len(buf) < INLINE_LIMIT:
            return bytes(buf) if not isinstance(obj, bytes) else obj
        return _ref_for(
            buf if isinstance(buf, memoryview) else memoryview(buf),
            {"y": 1},
            buffers,
            shm_put,
        )
    if isinstance(obj, dict):
        return {k: _extract(v, buffers, shm_put) for k, v in obj.items()}
    if isinstance(obj, msgpack.ExtType):
        # ExtType is a namedtuple — the tuple branch below would
        # flatten it into [code, data]; pass it through to msgpack
        return obj
    if isinstance(obj, (list, tuple)):
        return [_extract(v, buffers, shm_put) for v in obj]
    return obj


def _ref_for(
    buf: memoryview, desc: dict, buffers: list, shm_put: Optional[Callable]
) -> msgpack.ExtType:
    if shm_put is not None:
        key = shm_put(buf)
        if key is not None:
            return msgpack.ExtType(
                EXT_SHM_REF,
                msgpack.packb({**desc, "k": key, "n": buf.nbytes}),
            )
    idx = len(buffers)
    buffers.append(buf)
    return msgpack.ExtType(EXT_OOB_REF, msgpack.packb({**desc, "i": idx}))


def encode_oob(
    msg: dict,
    shm_put: Optional[Callable] = None,
    payload_info: Optional[dict] = None,
) -> bytearray:
    """Encode ``msg`` as one scatter-gather frame.

    Each extracted payload buffer is written into the frame exactly
    once (or diverted to the shared store via ``shm_put``); everything
    else packs into the small header. Returns the assembled frame —
    ``bytearray`` so callers can send slices without another copy.
    ``payload_info`` (when given) receives ``{"n", "bytes"}`` of the
    wire-extracted buffers — the codec's RpcStats feed."""
    buffers: list[memoryview] = []
    header = msgpack.packb(
        _extract(msg, buffers, shm_put), default=_default, use_bin_type=True
    )
    if payload_info is not None:
        payload_info["n"] = len(buffers)
        payload_info["bytes"] = sum(b.nbytes for b in buffers)
    table = []
    rel = 0
    for buf in buffers:
        rel = _align64(rel)
        table.append([rel, buf.nbytes])
        rel += buf.nbytes
    meta = msgpack.packb({"h": header, "b": table})
    payload_start = _align64(8 + len(meta))
    frame = bytearray(payload_start + rel)
    frame[0:4] = OOB_MAGIC
    frame[4:8] = len(meta).to_bytes(4, "little")
    frame[8 : 8 + len(meta)] = meta
    for (off, length), buf in zip(table, buffers):
        frame[payload_start + off : payload_start + off + length] = buf
    return frame


def decode_oob(data, shm_get: Optional[Callable] = None) -> dict:
    """Decode a scatter-gather frame.

    Arrays referenced through the buffer table come back as
    ``np.frombuffer`` views **over the received frame** — zero copies,
    read-only (mutate via ``.copy()`` when needed, same contract the
    legacy decoder already had). ``shm_get(descriptor) -> value``
    materializes store-resident payloads (array view over the shm
    segment, or bytes) and owns their pin lifetime
    (rpc.transport.ShmPinTracker)."""
    mv = memoryview(data)
    if bytes(mv[:4]) != OOB_MAGIC:
        raise ValueError("not an out-of-band frame")
    meta_len = int.from_bytes(mv[4:8], "little")
    meta = msgpack.unpackb(mv[8 : 8 + meta_len], raw=False)
    table = meta["b"]
    payload = mv[_align64(8 + meta_len) :]

    def hook(code: int, ext_data: bytes) -> Any:
        if code == EXT_OOB_REF:
            d = msgpack.unpackb(ext_data)
            off, length = table[d["i"]]
            raw = payload[off : off + length]
            if d.get("y"):
                return bytes(raw)
            return np.frombuffer(raw, dtype=np.dtype(d["d"])).reshape(d["s"])
        if code == EXT_SHM_REF:
            d = msgpack.unpackb(ext_data)
            if shm_get is None:
                raise RuntimeError(
                    "message references the shared object store but this "
                    "peer has none attached (negotiation bug)"
                )
            # shm_get materializes the value itself (array view over
            # the segment, or bytes) because pin lifetime must be tied
            # to the object it hands out — see transport.ShmPinTracker
            return shm_get(d)
        return _ext_hook(code, ext_data)

    return msgpack.unpackb(meta["h"], ext_hook=hook, raw=False)
