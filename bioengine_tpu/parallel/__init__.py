from bioengine_tpu.parallel.mesh import MeshSpec, VirtualMeshSpec, make_mesh
from bioengine_tpu.parallel.tensor_parallel import (
    CONV_TP_RULES,
    VIT_TP_RULES,
    make_tp_apply,
    shard_params,
    tp_param_specs,
)

__all__ = [
    "MeshSpec",
    "VirtualMeshSpec",
    "make_mesh",
    "CONV_TP_RULES",
    "VIT_TP_RULES",
    "make_tp_apply",
    "shard_params",
    "tp_param_specs",
]
