from bioengine_tpu.parallel.mesh import MeshSpec, make_mesh

__all__ = ["MeshSpec", "make_mesh"]
