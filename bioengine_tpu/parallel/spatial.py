"""Spatial (image-domain) parallelism with halo exchange.

The reference's closest analog is *serial* tiling: fibsem-mito-analysis
cuts a large EM image into 512^2 tiles and calls the model per tile over
RPC (ref apps/fibsem-mito-analysis/analysis_deployment.py:10-14), and
bioimageio blockwise prediction does the same in-process. Neither is
parallel. Here the first spatial axis — image height, or stack depth
for volumetric (B, D, H, W, C) models — is sharded over the mesh's
``sp`` axis and convolutional halos are exchanged with ``ppermute``
over ICI: one jitted program, N chips, no stitching artifacts. With
halo >= receptive radius the interior is bit-exact vs the unsharded
model; multi-layer models differ only within the receptive radius of
the GLOBAL borders, where block-level zero padding stands in for the
unsharded model's per-layer padding (see ``spatial_shard_apply``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bioengine_tpu.parallel.mesh import get_shard_map, named_axis_size


def halo_exchange(x: jax.Array, halo: int, axis_name: str) -> jax.Array:
    """Pad a block sharded on array axis 1 with ``halo`` slices from
    ring neighbours.

    x: (B, H_local, W, C) — or (B, D_local, H, W, C) for volumes —
    inside shard_map; only axis 1 is touched, so any rank works.
    Returns the block grown by 2*halo along axis 1. Edge shards receive
    zeros (same as a zero-padded unsharded conv).
    """
    if halo == 0:
        return x
    idx = jax.lax.axis_index(axis_name)
    n = named_axis_size(axis_name)
    top_rows = x[:, :halo]          # my first rows -> neighbour below...
    bot_rows = x[:, -halo:]         # my last rows -> neighbour above
    # Send my bottom rows DOWN the ring (shard i -> i+1) so each shard
    # receives its upper neighbour's bottom rows.
    from_above = jax.lax.ppermute(
        bot_rows, axis_name, [(i, (i + 1) % n) for i in range(n)]
    )
    # Send my top rows UP the ring (i -> i-1): receive lower neighbour's top.
    from_below = jax.lax.ppermute(
        top_rows, axis_name, [(i, (i - 1) % n) for i in range(n)]
    )
    # Zero out wrap-around contributions at the edges.
    from_above = jnp.where(idx == 0, jnp.zeros_like(from_above), from_above)
    from_below = jnp.where(
        idx == n - 1, jnp.zeros_like(from_below), from_below
    )
    return jnp.concatenate([from_above, x, from_below], axis=1)


def spatial_shard_apply(
    apply_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    halo: int,
    axis: str = "sp",
    rank: int = 4,
) -> Callable[[Any, jax.Array], jax.Array]:
    """Lift ``apply_fn`` to an SPMD program sharded on its first
    spatial axis: (B,H,W,C) height-sharded at ``rank=4``, volumetric
    (B,D,H,W,C) depth-sharded at ``rank=5``.

    The wrapped fn takes the FULL array; jit + shard_map split axis 1
    over ``axis``, exchange halos, run the model per-shard on the
    haloed block, and crop the halo off the output. Exact for models
    whose receptive-field radius <= halo and whose output stride is 1,
    with one caveat: within the receptive radius of the GLOBAL top and
    bottom borders, a multi-layer model sees block-level zero padding
    instead of the unsharded model's per-layer zero padding, so border
    slices can differ (a boundary-condition approximation of the same
    order as tiled/blended inference; interiors are bit-exact). A
    single conv layer matches everywhere.

    ``halo`` must not exceed the local shard extent (global size /
    n_shards): ppermute reaches immediate ring neighbours only.
    """
    shard_map = get_shard_map()

    spec = _axis1_spec(axis, rank)
    n_shards = mesh.shape[axis]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), spec),
        out_specs=spec,
    )
    def sharded(params, block):
        if halo > block.shape[1]:
            raise ValueError(
                f"halo {halo} exceeds the local shard extent "
                f"{block.shape[1]} (axis '{axis}' split {n_shards} ways) — "
                f"halo exchange reaches immediate neighbours only; use "
                f"fewer shards or a smaller halo"
            )
        haloed = halo_exchange(block, halo, axis)
        out = apply_fn(params, haloed)
        return out[:, halo:-halo] if halo else out

    jitted = jax.jit(sharded)

    def wrapper(params, x):
        if np.ndim(x) != rank:
            raise ValueError(
                f"spatial_shard_apply was built with rank={rank} but got a "
                f"rank-{np.ndim(x)} input — pass rank={np.ndim(x)} (4 for "
                f"(B,H,W,C) images, 5 for (B,D,H,W,C) volumes)"
            )
        return jitted(params, x)

    return wrapper


def _axis1_spec(axis: str, rank: int) -> P:
    """PartitionSpec sharding array axis 1 over ``axis``."""
    return P(None, axis, *([None] * (rank - 2)))


def shard_image(mesh: Mesh, image, axis: str = "sp"):
    """Place (B, H, W, C) or (B, D, H, W, C) with axis 1 (height /
    depth) sharded over ``axis``."""
    return jax.device_put(
        image,
        NamedSharding(mesh, _axis1_spec(axis, np.ndim(image))),
    )
