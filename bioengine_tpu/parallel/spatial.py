"""Spatial (image-domain) parallelism with halo exchange.

The reference's closest analog is *serial* tiling: fibsem-mito-analysis
cuts a large EM image into 512^2 tiles and calls the model per tile over
RPC (ref apps/fibsem-mito-analysis/analysis_deployment.py:10-14), and
bioimageio blockwise prediction does the same in-process. Neither is
parallel. Here the image's height axis is sharded over the mesh's ``sp``
axis and convolutional halos are exchanged with ``ppermute`` over ICI —
one jitted program, N chips, no stitching artifacts (exact, not
blended: every output pixel sees the same receptive field as the
unsharded model).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def halo_exchange(x: jax.Array, halo: int, axis_name: str) -> jax.Array:
    """Pad a height-sharded block with ``halo`` rows from ring neighbours.

    x: (B, H_local, W, C) inside shard_map. Returns
    (B, H_local + 2*halo, W, C). Edge shards receive zeros (same as a
    zero-padded unsharded conv).
    """
    if halo == 0:
        return x
    idx = jax.lax.axis_index(axis_name)
    n = jax.lax.axis_size(axis_name)
    top_rows = x[:, :halo]          # my first rows -> neighbour below...
    bot_rows = x[:, -halo:]         # my last rows -> neighbour above
    # Send my bottom rows DOWN the ring (shard i -> i+1) so each shard
    # receives its upper neighbour's bottom rows.
    from_above = jax.lax.ppermute(
        bot_rows, axis_name, [(i, (i + 1) % n) for i in range(n)]
    )
    # Send my top rows UP the ring (i -> i-1): receive lower neighbour's top.
    from_below = jax.lax.ppermute(
        top_rows, axis_name, [(i, (i - 1) % n) for i in range(n)]
    )
    # Zero out wrap-around contributions at the edges.
    from_above = jnp.where(idx == 0, jnp.zeros_like(from_above), from_above)
    from_below = jnp.where(
        idx == n - 1, jnp.zeros_like(from_below), from_below
    )
    return jnp.concatenate([from_above, x, from_below], axis=1)


def spatial_shard_apply(
    apply_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    halo: int,
    axis: str = "sp",
) -> Callable[[Any, jax.Array], jax.Array]:
    """Lift ``apply_fn`` (params, (B,H,W,C)) -> (B,H,W,C') to a
    height-sharded SPMD program.

    The wrapped fn takes the FULL image; jit + shard_map split H over
    ``axis``, exchange halos, run the model per-shard on the haloed
    block, and crop the halo off the output. Correct for models whose
    receptive-field radius <= halo and whose output stride is 1.
    """
    # jax >= 0.8 promotes shard_map to the top level
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(None, axis, None, None)),
        out_specs=P(None, axis, None, None),
    )
    def sharded(params, block):
        haloed = halo_exchange(block, halo, axis)
        out = apply_fn(params, haloed)
        return out[:, halo:-halo] if halo else out

    return jax.jit(sharded)


def shard_image(mesh: Mesh, image, axis: str = "sp"):
    """Place (B, H, W, C) with H sharded over ``axis``."""
    return jax.device_put(
        image, NamedSharding(mesh, P(None, axis, None, None))
    )
