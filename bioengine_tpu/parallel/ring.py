"""Ring attention — sequence/context parallelism over the mesh.

The reference has no sequence models and no sequence parallelism
(SURVEY.md §2.3, §5.7); this is a first-class new capability so the
framework scales transformer workloads (ViT embedders over giant token
counts, future sequence models) past one chip's HBM.

Design: shard the token axis over the ``sp`` mesh axis. Q blocks stay
resident; K/V blocks rotate around the ring with ``ppermute`` (ICI
neighbour hops) while a streaming-softmax accumulator (running max,
normalizer, weighted sum — the flash-attention recurrence) folds in one
block per step. Memory per chip is O(N/n) and the ICI transfer fully
overlaps with the block matmuls under XLA's scheduler.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bioengine_tpu.parallel.mesh import get_shard_map, named_axis_size


def _block_attn(q, k, v, m_prev, l_prev, o_prev, scale):
    """One streaming-softmax update. q/k/v: (B, H, Nq, d)/(B, H, Nk, d)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale          # (B,H,Nq,Nk)
    m_cur = jnp.max(s, axis=-1)                               # (B,H,Nq)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new[..., None])
    l_new = l_prev * jnp.exp(m_prev - m_new) + p.sum(-1)
    o_new = o_prev * jnp.exp(m_prev - m_new)[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Exact attention with K/V sharded over ``axis_name``.

    Called INSIDE shard_map; q, k, v: (B, H, N_local, d) per-shard
    blocks. Returns (B, H, N_local, d). Non-causal (bidirectional —
    images/embedding workloads); a causal variant can mask per-step.
    """
    n = named_axis_size(axis_name)
    scale = q.shape[-1] ** -0.5
    B, H, Nq, d = q.shape
    m0 = jnp.full((B, H, Nq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Nq), jnp.float32)
    o0 = jnp.zeros((B, H, Nq, d), jnp.float32)
    # Accumulators must carry the same device-varying type as the loop
    # body's outputs (which derive from the sp-sharded q/k/v blocks).
    # jax >= 0.8 renames pvary -> pcast(..., to='varying').
    if hasattr(jax.lax, "pcast"):
        m0, l0, o0 = jax.lax.pcast((m0, l0, o0), axis_name, to="varying")
    elif hasattr(jax.lax, "pvary"):
        m0, l0, o0 = jax.lax.pvary((m0, l0, o0), axis_name)
    # jax < 0.5 has neither: accumulators are implicitly device-varying

    qf = q.astype(jnp.float32)

    def fold(m, l, o, k_blk, v_blk):
        return _block_attn(
            qf,
            k_blk.astype(jnp.float32),
            v_blk.astype(jnp.float32),
            m,
            l,
            o,
            scale,
        )

    def step(i, carry):
        m, l, o, kv = carry
        k_blk, v_blk = kv
        m, l, o = fold(m, l, o, k_blk, v_blk)
        # Rotate K/V one hop around the ring for the next step.
        perm = [(j, (j + 1) % n) for j in range(n)]
        kv = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), (k_blk, v_blk)
        )
        return m, l, o, kv

    # Loop n-1 fold+rotate steps, then fold the final block outside the
    # loop — saves one full K/V ICI hop per attention call.
    m, l, o, (k_last, v_last) = jax.lax.fori_loop(
        0, n - 1, step, (m0, l0, o0, (k, v))
    )
    m, l, o = fold(m, l, o, k_last, v_last)
    return (o / l[..., None]).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = "sp"):
    """Build a jitted full-sequence attention fn with tokens sharded
    over ``axis``: (B, H, N, d) x3 -> (B, H, N, d).

    Drop-in for ``bioengine_tpu.models.vit.Attention(attn_fn=...)`` when
    a replica owns a multi-chip sub-mesh and sequences exceed one chip.
    """
    shard_map = get_shard_map()

    spec = P(None, None, axis, None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def sharded(q, k, v):
        return ring_attention(q, k, v, axis)

    return jax.jit(sharded)


def reference_attention(q, k, v):
    """Unsharded reference for tests: same math, one device."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
