"""Data-parallel training over a named mesh axis.

The *new* capability vs the reference: its Cellpose fine-tuning trains
on exactly one GPU (ref apps/cellpose-finetuning/main.py:3601-3632 — one
Serve replica with num_gpus=1, no torch.distributed anywhere, see
SURVEY.md §2.3). Here any pure train step becomes data-parallel by
construction: params replicated, batch sharded over ``dp``, and XLA
inserts the gradient all-reduce over ICI when it partitions the jitted
program.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_batch(mesh: Mesh, batch: Any, axis: str = "dp") -> Any:
    """Place a host pytree batch onto the mesh, leading dim sharded."""

    def put(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, batch)


def replicate(mesh: Mesh, tree: Any) -> Any:
    """Replicate a pytree (params / opt state) across the whole mesh."""
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree
    )


def jit_data_parallel_step(
    step_fn: Callable,
    mesh: Mesh,
    axis: str = "dp",
    donate_state: bool = True,
) -> Callable:
    """jit a pure ``(state, *batch) -> (state, metrics)`` step for DP.

    in_shardings: state replicated, every batch array sharded on its
    leading dim over ``axis``. XLA partitions the forward/backward and
    emits one fused all-reduce for the gradients — no explicit
    collective code, no NCCL analog (SURVEY.md §2.3 "collective
    backend" row).
    """
    state_sharding = NamedSharding(mesh, P())

    def sharded(x_ndim: int):
        return NamedSharding(mesh, P(axis, *([None] * (x_ndim - 1))))

    def wrapper(state, *batch):
        return step_fn(state, *batch)

    # Shardings are resolved per-call from actual args via jax.jit's
    # lazy in_shardings; simplest robust form: constrain inside.
    def constrained(state, *batch):
        state = jax.lax.with_sharding_constraint(state, state_sharding)
        batch = tuple(
            jax.lax.with_sharding_constraint(b, sharded(b.ndim)) for b in batch
        )
        return wrapper(state, *batch)

    return jax.jit(
        constrained, donate_argnums=(0,) if donate_state else ()
    )


def per_device_batch(global_batch: int, mesh: Mesh, axis: str = "dp") -> int:
    n = mesh.shape[axis]
    if global_batch % n:
        raise ValueError(
            f"global batch {global_batch} not divisible by {axis}={n}"
        )
    return global_batch // n
