"""Tensor parallelism — Megatron-style weight sharding via GSPMD.

Fills the ``tp`` axis reserved in parallel/mesh.py: column-parallel
first projections (qkv, MLP up) and row-parallel second projections
(attn out, MLP down), expressed as ``PartitionSpec`` rules over the
param tree and handed to XLA's SPMD partitioner, which inserts the
all-reduces over ICI (the "pick a mesh, annotate shardings, let XLA
insert collectives" recipe — there is no hand-written collective
here by design).

The reference has no tensor parallelism anywhere (SURVEY.md §2.3 —
its unit of parallelism is a whole Ray Serve replica); this is a
TPU-native capability for models whose weights outgrow one chip's
HBM: each chip holds ``1/tp`` of every sharded matrix.

Usage::

    mesh = make_mesh({"dp": 2, "tp": 4})
    apply_fn, params = make_tp_apply(model, mesh, params, VIT_TP_RULES)
    out = apply_fn(params, images)     # images sharded over dp
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Sequence

import jax
from flax import traverse_util
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Megatron layout for the ViT blocks (models/vit.py param names):
# column-parallel (shard output features) for qkv + MLP up, then
# row-parallel (shard input features) for the projections that follow,
# so each block needs exactly one all-reduce per matmul pair. LayerNorm,
# LayerScale, embeddings stay replicated (they're tiny).
VIT_TP_RULES: list[tuple[str, P]] = [
    (r"attn/qkv/kernel$", P(None, "tp")),
    (r"attn/qkv/bias$", P("tp")),
    (r"attn/proj/kernel$", P("tp", None)),
    (r"mlp/Dense_0/kernel$", P(None, "tp")),
    (r"mlp/Dense_0/bias$", P("tp")),
    (r"mlp/Dense_1/kernel$", P("tp", None)),
]

# UNet2D / CellposeNet conv kernels: shard output channels on the conv,
# input channels on the next — GSPMD propagates through the pointwise
# ops between them. (Conv kernel layout: (kh, kw, cin, cout).)
CONV_TP_RULES: list[tuple[str, P]] = [
    (r"Conv_\d+/kernel$", P(None, None, None, "tp")),
    (r"Conv_\d+/bias$", P("tp")),
]


def _divisible(spec: P, shape: tuple, mesh: Optional[Mesh]) -> bool:
    """A spec is usable only when every sharded dim divides by its mesh
    axis size (e.g. a 1-channel output conv can never shard on tp)."""
    if mesh is None:
        return True
    for dim, axis in enumerate(spec):
        if axis is None:
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        if any(a not in mesh.shape for a in axes):
            return False  # axis absent from this mesh: replicate
        size = 1
        for a in axes:
            size *= mesh.shape[a]
        if dim >= len(shape) or shape[dim] % size != 0:
            return False
    return True


def tp_param_specs(
    params: Any,
    rules: Sequence[tuple[str, P]],
    mesh: Optional[Mesh] = None,
) -> Any:
    """PartitionSpec tree for ``params``: first rule whose regex matches
    the ``/``-joined param path wins; unmatched params — and matched
    params whose shapes don't divide by the mesh axis — are
    replicated."""
    flat = traverse_util.flatten_dict(params)
    specs = {}
    for path, leaf in flat.items():
        joined = "/".join(str(p) for p in path)
        spec = next(
            (spec for pattern, spec in rules if re.search(pattern, joined)),
            P(),
        )
        if not _divisible(spec, getattr(leaf, "shape", ()), mesh):
            spec = P()
        specs[path] = spec
    return traverse_util.unflatten_dict(specs)


def shard_params(
    mesh: Mesh, params: Any, rules: Sequence[tuple[str, P]]
) -> tuple[Any, Any]:
    """Place ``params`` onto the mesh per the TP rules. Returns
    (sharded_params, shardings_tree)."""
    specs = tp_param_specs(params, rules, mesh)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(params, shardings), shardings


def make_tp_apply(
    model: Any,
    mesh: Mesh,
    params: Any,
    rules: Sequence[tuple[str, P]] = VIT_TP_RULES,
    data_spec: Optional[P] = None,
    out_spec: Optional[P] = None,
) -> tuple[Callable, Any]:
    """Jit ``model.apply`` with Megatron-sharded weights.

    ``data_spec`` defaults to batch-sharding over ``dp`` when the mesh
    has that axis (replicated over ``tp``), else fully replicated.
    Returns (apply_fn, sharded_params)."""
    if data_spec is None:
        data_spec = P("dp") if "dp" in mesh.axis_names else P()
    if out_spec is None:
        out_spec = data_spec
    sharded_params, shardings = shard_params(mesh, params, rules)
    apply_fn = jax.jit(
        lambda p, x: model.apply({"params": p}, x),
        in_shardings=(shardings, NamedSharding(mesh, data_spec)),
        out_shardings=NamedSharding(mesh, out_spec),
    )
    return apply_fn, sharded_params


def shard_fraction(sharded_params: Any) -> float:
    """Diagnostic: per-device bytes / total bytes — ~(1/tp) of the big
    matrices plus replicated smalls. Used by tests to prove weights are
    actually distributed, not replicated."""
    total = 0
    local = 0
    for leaf in jax.tree.leaves(sharded_params):
        total += leaf.nbytes
        local += leaf.addressable_shards[0].data.nbytes
    return local / total
