"""Device-mesh construction for BioEngine-TPU.

The framework's parallelism axes:

- ``dp`` — data parallel (batch sharding; gradients all-reduced over ICI)
- ``sp`` — spatial/sequence parallel (image tiles with halo exchange, or
  token-sequence shards for ring attention)
- ``tp`` — tensor parallel (Megatron-style weight sharding,
  parallel/tensor_parallel.py)

The reference has no device-mesh concept at all — its unit of parallelism
is a whole Ray Serve replica (ref apps/proxy_deployment.py:36-44). Here a
replica *owns* a mesh, and scaling happens in units of replicas, each with
a fixed sub-mesh, so XLA programs never need recompiling on scale events
(see SURVEY.md §7 "Replica elasticity vs. XLA's static world").
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh description, serializable into app manifests."""

    axes: Mapping[str, int]  # ordered axis name -> size; -1 = fill

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = dict(self.axes)
        fill_axes = [k for k, v in sizes.items() if v == -1]
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if n_devices % fixed:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes {sizes}"
            )
        remaining = n_devices // fixed
        if not fill_axes:
            if fixed != n_devices:
                raise ValueError(
                    f"Mesh {sizes} needs {fixed} devices, have {n_devices}"
                )
            return sizes
        if len(fill_axes) > 1:
            raise ValueError("At most one axis may be -1")
        sizes[fill_axes[0]] = remaining
        return sizes


@dataclasses.dataclass(frozen=True)
class VirtualMeshSpec:
    """Hardware-neutral mesh description for a whole DEPLOYMENT.

    The virtual-device layer (VirtualFlow's decoupling, PAPERS.md): a
    deployment declares ``stages`` (the cross-host pipeline axis — each
    stage placeable on a different host's chip lease) and per-stage
    ``axes`` (dp/tp over whatever chips that stage's lease resolves to,
    ``-1`` = fill). The SAME spec then maps onto a v5e-1, a v5e-8, a
    two-host mesh, or a forced-host-device CPU mesh: the planner
    (serving/mesh_plan.py) picks hosts, and each shard's engine resolves
    ``axes`` over its concrete lease via :meth:`stage_axes` — app code
    never names a topology.
    """

    stages: int = 1
    axes: Mapping[str, int] = dataclasses.field(
        default_factory=lambda: {"dp": -1}
    )

    def stage_axes(self, n_devices: int) -> dict[str, int]:
        """Resolve the per-stage axes over one stage's concrete lease."""
        return MeshSpec(dict(self.axes)).resolve(n_devices)

    def shape(self, n_devices_per_stage: int) -> dict[str, int]:
        """The logical mesh shape this spec yields on a concrete
        topology — ``pp`` (pipeline/stage axis) first, then the
        resolved per-stage axes."""
        out: dict[str, int] = {}
        if self.stages > 1:
            out["pp"] = self.stages
        out.update(self.stage_axes(n_devices_per_stage))
        return out


def make_mesh(
    axes: Mapping[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named Mesh over ``devices`` (default: all local devices).

    Device ordering follows JAX's enumeration, which on TPU follows the
    physical torus — adjacent mesh coordinates land on ICI neighbours, so
    ``psum`` over the innermost axis rides the fastest links.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = MeshSpec(axes).resolve(len(devices))
    arr = np.array(devices).reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes.keys()))


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_device_mesh(n: int = 1, axis: str = "dp") -> Mesh:
    """A mesh over the first ``n`` local devices (single-replica case)."""
    return make_mesh({axis: n}, jax.devices()[:n])


def get_shard_map():
    """The ``shard_map`` entry point across jax versions.

    jax >= 0.8 promotes it to the top level; older versions keep it in
    ``jax.experimental``. One shim so callers don't each carry the
    ladder (sibling of ``named_axis_size`` below)."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as fn
    return fn


def named_axis_size(axis_name: str) -> int:
    """Static size of a named mesh axis from inside ``shard_map``.

    ``jax.lax.axis_size`` where it exists; on older jax a ``psum`` of
    the literal 1 over the axis, which the tracer folds to a plain int
    (usable in Python loops building ppermute rings)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)
